"""Frontend tests: chat client streaming against a live chain server, the
proxy API routes, and the static pages."""

import asyncio
import threading

import pytest
import requests

from aiohttp import web

from generativeaiexamples_tpu.chains.server import create_app as chain_app
from generativeaiexamples_tpu.frontend.chat_client import ChatClient
from generativeaiexamples_tpu.frontend.server import create_app as frontend_app
from generativeaiexamples_tpu.utils.errors import ConfigError


def _serve(app):
    """Run an aiohttp app on a random port in a daemon thread."""
    loop = asyncio.new_event_loop()
    started = threading.Event()
    box = {}

    def run():
        asyncio.set_event_loop(loop)

        async def start():
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            box["port"] = runner.addresses[0][1]
            started.set()

        loop.run_until_complete(start())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(30)
    return f"http://127.0.0.1:{box['port']}", loop


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    """chain server (echo LLM + hash embedder) + frontend, both live."""
    from generativeaiexamples_tpu.chains.examples.developer_rag import QAChatbot
    from generativeaiexamples_tpu.chains.llm import EchoLLM
    from generativeaiexamples_tpu.embed.encoder import HashEmbedder
    from generativeaiexamples_tpu.utils.app_config import AppConfig
    from generativeaiexamples_tpu.utils.configuration import from_dict

    cfg = from_dict(AppConfig, {
        "llm": {"model_engine": "echo"},
        "embeddings": {"model_engine": "hash", "dimensions": 64},
        "text_splitter": {"chunk_size": 120, "chunk_overlap": 20},
    })
    example = QAChatbot(llm=EchoLLM(prefix="", tail_chars=4000),
                        embedder=HashEmbedder(dim=64), config=cfg)
    chain_url, chain_loop = _serve(chain_app(
        example, upload_dir=str(tmp_path_factory.mktemp("uploads"))))
    fe_url, fe_loop = _serve(frontend_app(ChatClient(chain_url)))
    yield chain_url, fe_url
    chain_loop.call_soon_threadsafe(chain_loop.stop)
    fe_loop.call_soon_threadsafe(fe_loop.stop)


def test_chat_client_roundtrip(stack, tmp_path):
    chain_url, _ = stack
    client = ChatClient(chain_url)
    doc = tmp_path / "facts.txt"
    doc.write_text("The ICI mesh links TPU chips at terabit speeds.")
    client.upload_documents([str(doc)])

    hits = client.search("ICI mesh", num_docs=2)
    assert hits and hits[0]["source"] == "facts.txt"

    chunks = list(client.predict("What links TPU chips?", num_tokens=4000))
    assert chunks[-1] is None  # sentinel parity (chat_client.py:72-99)
    text = "".join(c for c in chunks if c)
    assert "ICI" in text


def test_frontend_pages_and_static(stack):
    _, fe_url = stack
    for path, marker in [("/content/converse", "Converse"),
                         ("/content/kb", "Knowledge Base"),
                         ("/static/style.css", "--accent")]:
        resp = requests.get(f"{fe_url}{path}", timeout=10)
        assert resp.ok
        assert marker in resp.text
    # root redirects to converse
    resp = requests.get(fe_url, timeout=10)
    assert resp.url.endswith("/content/converse")


def test_frontend_proxy_generate_and_search(stack, tmp_path):
    _, fe_url = stack
    doc = tmp_path / "notes.txt"
    doc.write_text("The MXU performs 128x128 matmuls per cycle.")
    with open(doc, "rb") as f:
        resp = requests.post(f"{fe_url}/api/upload",
                             files={"file": ("notes.txt", f)}, timeout=30)
    assert resp.ok, resp.text
    assert resp.json()["status"] == "ingested"

    table = requests.get(f"{fe_url}/api/kb", timeout=10).json()
    assert any(e["filename"] == "notes.txt" and e["status"] == "ingested"
               for e in table)

    resp = requests.post(f"{fe_url}/api/generate",
                         json={"question": "What does the MXU do?",
                               "use_knowledge_base": True,
                               "num_tokens": 4000},
                         stream=True, timeout=30)
    body = b"".join(resp.iter_content(chunk_size=64)).decode()
    assert "MXU" in body

    docs = requests.post(f"{fe_url}/api/search",
                         json={"content": "matmul", "num_docs": 4},
                         timeout=10).json()
    assert docs and "notes.txt" in {d["source"] for d in docs}


def test_speech_gated():
    try:
        import riva.client  # noqa: F401
        pytest.skip("riva installed")
    except ImportError:
        pass
    from generativeaiexamples_tpu.frontend.speech import ASRClient, TTSClient
    with pytest.raises(ConfigError, match="riva"):
        ASRClient()
    with pytest.raises(ConfigError, match="riva"):
        TTSClient()


# ------------------------------------------------------------------ speech

class FakeASR:
    def transcribe(self, audio):
        return f"transcript of {len(audio)} bytes"


class FakeTTS:
    def synthesize(self, text):
        return b"RIFFfake-wav:" + text.encode()[:16]


def test_speech_routes_with_clients():
    """Mic + TTS wiring of the converse page (reference: converse.py:65)."""
    from generativeaiexamples_tpu.frontend.chat_client import ChatClient
    client = ChatClient("http://127.0.0.1:9")   # never called by these routes
    app = frontend_app(client, asr=FakeASR(), tts=FakeTTS())
    base, _ = _serve(app)

    cfg = requests.get(f"{base}/api/speech/config", timeout=10).json()
    assert cfg == {"asr": True, "tts": True}

    r = requests.post(f"{base}/api/speech/transcribe", data=b"audio-bytes",
                      timeout=10)
    assert r.ok and r.json()["text"] == "transcript of 11 bytes"

    r = requests.post(f"{base}/api/speech/tts", json={"text": "hello"},
                      timeout=10)
    assert r.ok
    assert r.headers["Content-Type"].startswith("audio/")
    assert r.content.startswith(b"RIFFfake-wav:hello")

    page = requests.get(f"{base}/content/converse", timeout=10).text
    assert 'id="mic"' in page and 'id="usetts"' in page
    assert "/api/speech/transcribe" in page


def test_speech_routes_degrade_without_riva():
    from generativeaiexamples_tpu.frontend.chat_client import ChatClient
    client = ChatClient("http://127.0.0.1:9")
    app = frontend_app(client)   # no RIVA_API_URI -> disabled
    base, _ = _serve(app)
    cfg = requests.get(f"{base}/api/speech/config", timeout=10).json()
    assert cfg == {"asr": False, "tts": False}
    r = requests.post(f"{base}/api/speech/transcribe", data=b"x", timeout=10)
    assert r.status_code == 501
    r = requests.post(f"{base}/api/speech/tts", json={"text": "x"},
                      timeout=10)
    assert r.status_code == 501
