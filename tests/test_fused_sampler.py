"""Fused vocab-tiled unembed+sampling (ops/fused_sampler.py) vs the
materialized penalize-then-sample reference, plus the memory contract:
the decode round must never materialize (B, V) penalized logits or
(B, V) bool masks — asserted structurally on the round's jaxpr.

The fused path is SAMPLE-EXACT against ``sample_reference_tiled`` (the
(B, V) oracle sharing its per-tile Gumbel layout) whenever the kept
truncation prefix fits the candidate carry — pinned here under fixed
keys, mixed greedy/sampling rows, repetition penalties, bitfield bans
and multi-token sequence bans."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from generativeaiexamples_tpu.ops.fused_sampler import (
    choose_tile, fused_unembed_sample, sample_reference_tiled)
from generativeaiexamples_tpu.ops.sampling import (
    NEG_INF, apply_repetition_penalty, mask_words, pack_mask,
    pack_mask_np, set_token_bits, unpack_mask)

V, TILE = 128, 32


def _mk(B, seed=0, sharp=1.0):
    ks = jax.random.split(jax.random.key(seed), 4)
    logits = jax.random.normal(ks[0], (B, V), jnp.float32) * sharp
    seen = jax.random.bernoulli(ks[1], 0.3, (B, V))
    banned = jax.random.bernoulli(ks[2], 0.05, (B, V))
    return logits, seen, banned, ks[3]


def _tile_fn(logits):
    def f(t0, tile):
        return jax.lax.dynamic_slice_in_dim(logits, t0, tile, axis=1)
    return f


def _oracle_penalize(logits, seen, banned, rep_pen, ban_tok=None,
                     ban_hit=None):
    pen = apply_repetition_penalty(logits, seen, rep_pen)
    pen = jnp.where(banned, NEG_INF, pen)
    if ban_tok is not None:
        pen = np.asarray(pen).copy()
        bt, bh = np.asarray(ban_tok), np.asarray(ban_hit)
        for b in range(pen.shape[0]):
            for s in range(bt.shape[1]):
                if bh[b, s]:
                    pen[b, bt[b, s]] = NEG_INF
        pen = jnp.asarray(pen)
    return pen


@pytest.mark.parametrize("temp,top_k,top_p", [
    ([0.8, 1.3, 0.0, 1.0], [0, 5, 1, 0], [0.0, 0.0, 0.0, 0.9]),
    ([1.0, 1.0, 0.7, 2.0], [3, 1, 0, 8], [0.9, 0.0, 0.95, 0.5]),
])
def test_fused_matches_reference_sampler(temp, top_k, top_p):
    """Same key ⇒ IDENTICAL tokens as the materialized oracle, across
    mixed greedy rows (temp 0 / top_k 1), truncated and untruncated
    sampling, penalties and both ban forms. cand_k=V ⇒ exact for any
    truncation width."""
    B = len(temp)
    logits, seen, banned, key = _mk(B, seed=1)
    rep_pen = jnp.asarray([1.0, 1.4, 1.1, 1.2], jnp.float32)
    ban_tok = jnp.asarray([[3, 7], [0, 0], [50, 2], [9, 9]], jnp.int32)
    ban_hit = jnp.asarray([[True, False], [False, False],
                           [True, True], [False, True]])
    temp = jnp.asarray(temp, jnp.float32)
    top_k = jnp.asarray(top_k, jnp.int32)
    top_p = jnp.asarray(top_p, jnp.float32)

    got = fused_unembed_sample(
        _tile_fn(logits), V, key=key, temp=temp, top_k=top_k,
        top_p=top_p, rep_pen=rep_pen, seen_words=pack_mask(seen),
        banned_words=pack_mask(banned), ban_tok=ban_tok, ban_hit=ban_hit,
        tile=TILE, cand_k=V)
    pen = _oracle_penalize(logits, seen, banned, rep_pen, ban_tok,
                           ban_hit)
    want = sample_reference_tiled(pen, key, temp, top_k, top_p, TILE)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fused_exact_when_prefix_fits_candidate_carry():
    """A small candidate carry stays exact as long as the kept top-k/p
    prefix fits in it (the vLLM-style candidate cap contract)."""
    B = 3
    logits, seen, banned, key = _mk(B, seed=2, sharp=4.0)
    temp = jnp.full((B,), 0.9, jnp.float32)
    top_k = jnp.asarray([4, 8, 2], jnp.int32)       # <= cand_k
    top_p = jnp.zeros((B,), jnp.float32)
    rep_pen = jnp.full((B,), 1.2, jnp.float32)
    got = fused_unembed_sample(
        _tile_fn(logits), V, key=key, temp=temp, top_k=top_k,
        top_p=top_p, rep_pen=rep_pen, seen_words=pack_mask(seen),
        banned_words=pack_mask(banned), tile=TILE, cand_k=8)
    pen = _oracle_penalize(logits, seen, banned, rep_pen)
    want = sample_reference_tiled(pen, key, temp, top_k, top_p, TILE)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fused_same_key_deterministic():
    B = 2
    logits, seen, banned, key = _mk(B, seed=3)
    kw = dict(key=key, temp=jnp.ones((B,)), top_k=jnp.zeros((B,), jnp.int32),
              top_p=jnp.zeros((B,)), rep_pen=jnp.ones((B,)),
              seen_words=pack_mask(seen), banned_words=pack_mask(banned),
              tile=TILE)
    a = fused_unembed_sample(_tile_fn(logits), V, **kw)
    b = fused_unembed_sample(_tile_fn(logits), V, **kw)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fused_greedy_flag_is_pure_argmax():
    B = 2
    logits, seen, banned, key = _mk(B, seed=4)
    rep_pen = jnp.asarray([1.3, 1.0], jnp.float32)
    got = fused_unembed_sample(
        _tile_fn(logits), V, key=key, temp=jnp.ones((B,)),
        top_k=jnp.ones((B,), jnp.int32), top_p=jnp.zeros((B,)),
        rep_pen=rep_pen, seen_words=pack_mask(seen),
        banned_words=pack_mask(banned), tile=TILE, greedy=True)
    pen = _oracle_penalize(logits, seen, banned, rep_pen)
    np.testing.assert_array_equal(
        np.asarray(got), np.argmax(np.asarray(pen), -1).astype(np.int32))


def test_banned_token_never_sampled():
    B = 2
    logits, seen, _, key = _mk(B, seed=5)
    banned = jnp.zeros((B, V), bool).at[:, :V // 2].set(True)
    for i in range(6):
        tok = fused_unembed_sample(
            _tile_fn(logits), V, key=jax.random.fold_in(key, i),
            temp=jnp.ones((B,)), top_k=jnp.zeros((B,), jnp.int32),
            top_p=jnp.zeros((B,)), rep_pen=jnp.ones((B,)),
            seen_words=pack_mask(seen), banned_words=pack_mask(banned),
            tile=TILE)
        assert (np.asarray(tok) >= V // 2).all()


# ---------------------------------------------------- mask bitfields


def test_pack_unpack_roundtrip_and_numpy_twin():
    for vocab in (31, 32, 33, 264, 128):
        mask = np.asarray(
            jax.random.bernoulli(jax.random.key(vocab), 0.4, (3, vocab)))
        words = pack_mask(jnp.asarray(mask))
        assert words.shape == (3, mask_words(vocab))
        assert words.dtype == jnp.uint32
        np.testing.assert_array_equal(
            np.asarray(unpack_mask(words, vocab)), mask)
        np.testing.assert_array_equal(np.asarray(words),
                                      pack_mask_np(mask))


def test_set_token_bits_masked_rows_untouched():
    words = pack_mask(jnp.zeros((3, 64), bool))
    toks = jnp.asarray([5, 33, 63], jnp.int32)
    on = jnp.asarray([True, False, True])
    out = unpack_mask(set_token_bits(words, toks, on), 64)
    want = np.zeros((3, 64), bool)
    want[0, 5] = True
    want[2, 63] = True
    np.testing.assert_array_equal(np.asarray(out), want)


def test_choose_tile_alignment():
    assert choose_tile(4096, 512) == 512
    assert choose_tile(32000, 4096) == 4000      # divisor, 32-aligned
    assert choose_tile(264, 4096) == 264         # 32-indivisible: whole
    assert choose_tile(128, 50) == 32            # rounds down to words


# ------------------------------------------ engine-level memory proof


def _jaxprs_in(val):
    if isinstance(val, jax.core.ClosedJaxpr):
        yield val.jaxpr
    elif isinstance(val, jax.core.Jaxpr):
        yield val
    elif isinstance(val, (list, tuple)):
        for v in val:
            yield from _jaxprs_in(v)


def _walk_avals(jaxpr, out):
    for eqn in jaxpr.eqns:
        out.extend(v.aval for v in eqn.outvars)
        for val in eqn.params.values():
            for sub in _jaxprs_in(val):
                _walk_avals(sub, out)


def test_decode_round_never_materializes_vocab(monkeypatch):
    """Structural memory contract for the acceptance criterion: trace
    the engine's ACTUAL fused decode round on a tiny 32-divisible-vocab
    config forced to multiple vocab tiles, and assert NO intermediate
    anywhere in the jaxpr (scan bodies included) carries a full
    (rows, V) array — penalized logits, bool seen/banned masks and the
    unembed output all stay tiled or packed."""
    from generativeaiexamples_tpu.engine import Engine, EngineConfig
    from generativeaiexamples_tpu.models import llama
    from generativeaiexamples_tpu.models.configs import LlamaConfig
    from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer

    vocab = 288                                   # 9 mask words, 3 tiles
    monkeypatch.setenv("SAMPLER_TILE", "96")
    monkeypatch.setenv("SAMPLER_CAND_K", "16")
    cfg = LlamaConfig(vocab_size=vocab, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16,
                      max_position_embeddings=256)
    params = llama.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    eng = Engine(params, cfg, ByteTokenizer(), EngineConfig(
        max_slots=4, max_input_length=64, max_output_length=32,
        prefill_buckets=(16, 32, 64), dtype="float32", max_queue=8))
    try:
        assert eng._fused_tail, "fused tail must be the default off-mesh"
        ba = 2
        fn = eng._make_round(eng._windows[0], 2, False, ba)
        jaxpr = jax.make_jaxpr(fn)(
            eng.params, eng._state, jax.random.key(1),
            jnp.zeros((ba,), jnp.int32)).jaxpr
        avals = []
        _walk_avals(jaxpr, avals)
        offenders = [a for a in avals
                     if getattr(a, "ndim", 0) >= 2
                     and a.shape[-1] == vocab]
        assert not offenders, (
            f"decode round materializes vocab-wide intermediates: "
            f"{[(a.shape, str(a.dtype)) for a in offenders]}")
        # sanity: the trace really saw the vocab work (tiled)
        assert any(getattr(a, "ndim", 0) >= 2 and a.shape[-1] == 96
                   for a in avals), "expected (rows, tile) intermediates"
    finally:
        eng.stop()


@pytest.mark.parametrize("storage", ["raw", "tied", "int8", "int4",
                                     "int4_grouped"])
def test_lm_head_tile_matches_full_unembed(storage):
    """Tile-sliced projection == the materialized unembed for EVERY
    lm_head storage the repo serves: tied embedding, raw (D, V), and the
    quantized dicts (whose packing runs along the reduction axis, so an
    output-axis slice stays a valid QTensor)."""
    from generativeaiexamples_tpu.models import llama
    from generativeaiexamples_tpu.models.configs import LlamaConfig
    from generativeaiexamples_tpu.ops.quant import (quantize_tensor,
                                                    quantize_tensor_grouped)

    cfg = LlamaConfig(vocab_size=V, hidden_size=64, intermediate_size=128,
                      num_layers=1, num_heads=4, num_kv_heads=2,
                      head_dim=16, max_position_embeddings=64)
    params = llama.init_params(cfg, jax.random.key(6), dtype=jnp.float32)
    if storage == "tied":
        params = {k: v for k, v in params.items() if k != "lm_head"}
    elif storage != "raw":
        head = params["lm_head"]
        params = dict(params)
        if storage == "int8":
            params["lm_head"] = quantize_tensor(head, bits=8)
        elif storage == "int4":
            params["lm_head"] = quantize_tensor(head, bits=4)
        else:
            params["lm_head"] = quantize_tensor_grouped(head,
                                                        group_size=32)
    h = jax.random.normal(jax.random.key(8), (3, 64), jnp.float32)
    want = llama.unembed(params, cfg, h[:, None, :])[:, 0]
    hn = llama.unembed_norm(params, cfg, h)
    tile = 32
    got = jnp.concatenate(
        [llama.lm_head_tile(params, cfg, hn, jnp.int32(t0), tile)
         for t0 in range(0, V, tile)], axis=-1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


# ------------------------------------------- tp-sharded tile stream


def _tp_setup(B, tp=2, seed=3):
    """Shared fixture pieces for the sharded-stream parity tests: a tp
    mesh over the virtual CPU devices, penalization state, and the
    matched tile size (single-chip stream pinned to the sharded tile so
    both consume the SAME global Gumbel field)."""
    from generativeaiexamples_tpu.parallel import MeshPlan, make_mesh

    mesh = make_mesh(MeshPlan(tp=tp), jax.devices()[:tp])
    logits, seen, banned, key = _mk(B, seed=seed)
    tile = choose_tile(V // tp)
    return mesh, logits, seen, banned, key, tile


def _raw_local_tile_fn(head_key):
    def f(head_local, hn, t0, tile):
        sl = jax.lax.dynamic_slice_in_dim(head_local[head_key], t0,
                                          tile, axis=1)
        return jax.lax.dot_general(
            hn, sl, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    return f


def test_sharded_sample_exact_vs_single_chip_and_oracle():
    """fused_unembed_sample_tp is SAMPLE-EXACT against both the
    single-chip stream (same tile size => same noise) and the
    materialized oracle — greedy rows, truncated rows, untruncated rows
    — with the per-shard carries merged across the tp axis."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from generativeaiexamples_tpu.ops.fused_sampler import (
        fused_unembed_sample_tp)

    B = 5
    mesh, logits, seen, banned, key, tile = _tp_setup(B)
    temp = jnp.asarray([0.8, 1.3, 0.0, 1.0, 0.9], jnp.float32)
    top_k = jnp.asarray([0, 5, 1, 0, 16], jnp.int32)
    top_p = jnp.asarray([0.0, 0.0, 0.0, 0.9, 0.8], jnp.float32)
    rep = jnp.full((B,), 1.15, jnp.float32)
    seen_w, ban_w = pack_mask(seen), pack_mask(banned)
    # identity "projection": hn IS the logits, the head the identity —
    # isolates the stream/merge math from any matmul
    eye = jax.device_put(jnp.eye(V, dtype=jnp.float32),
                         NamedSharding(mesh, P(None, "tp")))

    ref = fused_unembed_sample(_tile_fn(logits), V, key=key, temp=temp,
                               top_k=top_k, top_p=top_p, rep_pen=rep,
                               seen_words=seen_w, banned_words=ban_w,
                               tile=tile)
    got = jax.jit(lambda hd, h: fused_unembed_sample_tp(
        mesh, "tp", {"lm_head": hd}, {"lm_head": P(None, "tp")},
        _raw_local_tile_fn("lm_head"), V, hn=h, key=key, temp=temp,
        top_k=top_k, top_p=top_p, rep_pen=rep, seen_words=seen_w,
        banned_words=ban_w))(eye, logits)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    oracle = sample_reference_tiled(
        _oracle_penalize(logits, seen, banned, rep), key, temp, top_k,
        top_p, tile=tile)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(oracle))

    # greedy variant: running-argmax merge, lowest-shard tie rule
    g_ref = fused_unembed_sample(_tile_fn(logits), V, key=key, temp=temp,
                                 top_k=top_k, top_p=top_p, rep_pen=rep,
                                 seen_words=seen_w, banned_words=ban_w,
                                 greedy=True, tile=tile)
    g_got = jax.jit(lambda hd, h: fused_unembed_sample_tp(
        mesh, "tp", {"lm_head": hd}, {"lm_head": P(None, "tp")},
        _raw_local_tile_fn("lm_head"), V, hn=h, key=key, temp=temp,
        top_k=top_k, top_p=top_p, rep_pen=rep, seen_words=seen_w,
        banned_words=ban_w, greedy=True))(eye, logits)
    np.testing.assert_array_equal(np.asarray(g_got), np.asarray(g_ref))


def test_sharded_verify_verdict_exact_vs_oracle():
    """fused_verify_sample_tp produces IDENTICAL accept/resample
    verdicts to the materialized oracle under a fixed key/uniforms —
    the draft's scaled logit crossing shards via psum, the residual
    Gumbel-argmax via the running-max merge."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from generativeaiexamples_tpu.ops.fused_sampler import (
        fused_verify_sample, fused_verify_sample_tp,
        verify_reference_tiled)

    R = 6
    mesh, logits, seen, banned, key, tile = _tp_setup(R, seed=5)
    temp = jnp.asarray([0.9, 1.1, 0.0, 1.0, 0.8, 1.2], jnp.float32)
    top_k = jnp.asarray([0, 6, 1, 0, 12, 0], jnp.int32)
    top_p = jnp.asarray([0.0, 0.0, 0.0, 0.9, 0.0, 0.85], jnp.float32)
    rep = jnp.full((R,), 1.1, jnp.float32)
    # drafts on BOTH shards' vocab halves, plus a -1 bonus row
    drafts = jnp.asarray([3, 100, 64, -1, 127, 40], jnp.int32)
    u = jax.random.uniform(jax.random.key(17), (R,))
    seen_w, ban_w = pack_mask(seen), pack_mask(banned)
    eye = jax.device_put(jnp.eye(V, dtype=jnp.float32),
                         NamedSharding(mesh, P(None, "tp")))

    a_ref, o_ref = fused_verify_sample(
        _tile_fn(logits), V, key=key, u=u, temp=temp, top_k=top_k,
        top_p=top_p, rep_pen=rep, seen_words=seen_w, banned_words=ban_w,
        draft_ids=drafts, tile=tile)
    a_got, o_got = jax.jit(lambda hd, h: fused_verify_sample_tp(
        mesh, "tp", {"lm_head": hd}, {"lm_head": P(None, "tp")},
        _raw_local_tile_fn("lm_head"), V, hn=h, key=key, u=u, temp=temp,
        top_k=top_k, top_p=top_p, rep_pen=rep, seen_words=seen_w,
        banned_words=ban_w, draft_ids=drafts))(eye, logits)
    np.testing.assert_array_equal(np.asarray(a_got), np.asarray(a_ref))
    np.testing.assert_array_equal(np.asarray(o_got), np.asarray(o_ref))

    a_orc, o_orc = verify_reference_tiled(
        _oracle_penalize(logits, seen, banned, rep), key, u, temp,
        top_k, top_p, drafts, tile=tile)
    np.testing.assert_array_equal(np.asarray(a_got), np.asarray(a_orc))
    np.testing.assert_array_equal(np.asarray(o_got), np.asarray(o_orc))


@pytest.mark.parametrize("storage", ["raw", "tied", "int8", "int4",
                                     "int4_grouped"])
def test_sharded_head_storage_parity(storage):
    """The sharded tail serves EVERY lm_head storage: the local shard of
    a tied embedding / raw head / quantized dict (placed per
    llama.lm_head_specs) projects its vocab half exactly like the
    single-chip tile stream projects the same global range — pinned by
    greedy token equality against the single-chip fused sampler."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from generativeaiexamples_tpu.models import llama
    from generativeaiexamples_tpu.models.configs import LlamaConfig
    from generativeaiexamples_tpu.ops.fused_sampler import (
        fused_unembed_sample_tp)
    from generativeaiexamples_tpu.ops.quant import (quantize_tensor,
                                                    quantize_tensor_grouped)
    from generativeaiexamples_tpu.parallel import MeshPlan, make_mesh

    cfg = LlamaConfig(vocab_size=V, hidden_size=64, intermediate_size=128,
                      num_layers=1, num_heads=4, num_kv_heads=2,
                      head_dim=16, max_position_embeddings=64)
    params = llama.init_params(cfg, jax.random.key(6), dtype=jnp.float32)
    if storage == "tied":
        params = {k: v for k, v in params.items() if k != "lm_head"}
    elif storage != "raw":
        head = params["lm_head"]
        params = dict(params)
        if storage == "int8":
            params["lm_head"] = quantize_tensor(head, bits=8)
        elif storage == "int4":
            params["lm_head"] = quantize_tensor(head, bits=4)
        else:
            params["lm_head"] = quantize_tensor_grouped(head,
                                                        group_size=32)
    B = 3
    mesh = make_mesh(MeshPlan(tp=2), jax.devices()[:2])
    hn = jax.random.normal(jax.random.key(8), (B, 64), jnp.float32)
    _, seen, banned, key = _mk(B, seed=9)
    seen_w, ban_w = pack_mask(seen), pack_mask(banned)
    temp = jnp.zeros((B,), jnp.float32)       # greedy rows
    top_k = jnp.ones((B,), jnp.int32)
    top_p = jnp.zeros((B,), jnp.float32)
    rep = jnp.full((B,), 1.2, jnp.float32)
    tile = choose_tile(V // 2)

    ref = fused_unembed_sample(
        lambda t0, t: llama.lm_head_tile(params, cfg, hn, t0, t), V,
        key=key, temp=temp, top_k=top_k, top_p=top_p, rep_pen=rep,
        seen_words=seen_w, banned_words=ban_w, greedy=True, tile=tile)

    subtree = llama.lm_head_subtree(params)
    specs = llama.lm_head_specs(params, mesh)
    placed = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        subtree, specs)
    got = jax.jit(lambda head, h: fused_unembed_sample_tp(
        mesh, "tp", head, specs,
        lambda head_local, rows, t0, t: llama.lm_head_tile(
            head_local, cfg, rows, t0, t),
        V, hn=h, key=key, temp=temp, top_k=top_k, top_p=top_p,
        rep_pen=rep, seen_words=seen_w, banned_words=ban_w,
        greedy=True))(placed, hn)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_tp_shardable_geometry_rule():
    from generativeaiexamples_tpu.ops.fused_sampler import tp_shardable

    assert tp_shardable(320, 2)          # 160-token shards, whole words
    assert tp_shardable(128, 4)          # 32-token shards
    assert not tp_shardable(320, 4)      # 80 % 32 != 0
    assert not tp_shardable(130, 2)      # 65 % 32 != 0
    assert not tp_shardable(320, 3)      # uneven split
    assert not tp_shardable(320, 1)      # single chip: not a tp stream
