"""Pipeline parallelism: GPipe-style microbatched schedule over the ``pp``
mesh axis.

The reference gets pipeline parallelism from the TRT-LLM engine build
(reference: model_server/__main__.py:99-104 ``--pipeline-parallelism``,
conversion_scripts/llama/build.py:516 ``pp_size`` in the Mapping). TPU-native
version: every device holds ``L/pp`` contiguous layers (the same leading-L
sharding the param specs already use), microbatches stream through the
stages, and activations hop stage->stage with ``lax.ppermute`` over ICI —
one SPMD program, no per-rank processes.

Schedule: ``M`` microbatches over ``pp`` stages takes ``M + pp - 1`` ticks.
Each tick every stage (a) picks its input — the embedded microbatch for
stage 0, the activation received from the previous stage otherwise —
(b) runs its local layer stack, (c) ppermutes the result forward. The last
stage writes logits into the output buffer for the microbatch it just
finished. Bubble fraction is the usual ``(pp-1)/(M+pp-1)``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models import llama
from ..models.configs import LlamaConfig
from ..utils.errors import ShardingError
from .compat import pcast, shard_map


def pipeline_forward(mesh: Mesh, params: llama.Params, cfg: LlamaConfig,
                     tokens: jax.Array, positions: jax.Array,
                     n_microbatches: int = 2,
                     kv_valid_len: jax.Array | None = None) -> jax.Array:
    """Forward pass with the layer stack pipelined over the ``pp`` axis.

    tokens/positions: (B, S); B must divide into ``n_microbatches``.
    kv_valid_len: optional (B,) valid-token count per row (padding mask
    for attention), sliced per microbatch like the tokens.
    Embedding and the output head are replicated across stages (they are
    small next to the layer stack); only stage 0 consumes the embedding and
    only the last stage's logits survive. Returns (B, S, V) float32 logits,
    replicated over pp.
    """
    pp = mesh.shape["pp"]
    B, S = tokens.shape
    M = n_microbatches
    if cfg.num_layers % pp:
        raise ShardingError(
            f"num_layers {cfg.num_layers} not divisible by pp={pp} "
            f"(the layers%pp check of the reference, build.py:519-521)")
    if B % M:
        raise ShardingError(f"batch {B} not divisible by "
                            f"n_microbatches={M}")
    mb = B // M

    def stage_fn(layers, embed, tokens, positions, valid):
        stage = jax.lax.axis_index("pp")
        is_first = stage == 0
        is_last = stage == pp - 1

        def tick(carry, t):
            recv, outbuf = carry
            my_mb = t - stage                  # microbatch at this stage now
            active = (my_mb >= 0) & (my_mb < M)
            idx = jnp.clip(my_mb, 0, M - 1) * mb
            tok_mb = jax.lax.dynamic_slice(tokens, (idx, 0), (mb, S))
            pos_mb = jax.lax.dynamic_slice(positions, (idx, 0), (mb, S))
            val_mb = jax.lax.dynamic_slice(valid, (idx,), (mb,))
            h_in = jnp.where(is_first, jnp.take(embed, tok_mb, axis=0), recv)
            h_out = llama.run_layers(layers, cfg, h_in, pos_mb,
                                     kv_valid_len=val_mb)
            # the last stage commits hidden states for its (valid)
            # microbatch; others re-write what is already there
            current = jax.lax.dynamic_slice(outbuf, (idx, 0, 0), h_out.shape)
            outbuf = jax.lax.dynamic_update_slice(
                outbuf, jnp.where(active & is_last, h_out, current),
                (idx, 0, 0))
            # hop activations to the next stage (nothing enters stage 0)
            recv_next = jax.lax.ppermute(
                h_out, "pp", [(i, i + 1) for i in range(pp - 1)])
            return (recv_next, outbuf), None

        # carries become device-varying after axis_index/ppermute; mark the
        # initial values as varying over pp so the scan types line up
        recv0 = pcast(jnp.zeros((mb, S, cfg.hidden_size), embed.dtype),
                      ("pp",), to="varying")
        outbuf0 = pcast(
            jnp.zeros((B, S, cfg.hidden_size), embed.dtype),
            ("pp",), to="varying")
        (_, outbuf), _ = jax.lax.scan(
            tick, (recv0, outbuf0), jnp.arange(M + pp - 1))
        # only the last stage holds real hidden states; replicate across pp
        # (a (B,S,D) psum — V/D times cheaper than exchanging logits)
        return jax.lax.psum(
            jnp.where(is_last, outbuf, jnp.zeros_like(outbuf)), "pp")

    if kv_valid_len is None:
        # every position valid: same in-sequence causal masking as the
        # unpipelined forward's default
        kv_valid_len = jnp.full((B,), S, jnp.int32)
    layer_specs = jax.tree.map(lambda _: P("pp"), params["layers"])
    hidden = shard_map(
        stage_fn, mesh=mesh,
        in_specs=(layer_specs, P(), P(), P(), P()),
        out_specs=P())(
        params["layers"], params["embed"], tokens, positions,
        kv_valid_len.astype(jnp.int32))
    # unembed once, outside the pipeline (head weights are pp-replicated)
    return llama.unembed(params, cfg, hidden)


def pipeline_loss_fn(mesh: Mesh, cfg: LlamaConfig, n_microbatches: int = 2):
    """Cross-entropy loss with the forward pipelined over pp — drop-in for
    a pp>1 training step (grads flow through ppermute/scan)."""
    fwd = partial(pipeline_forward, mesh, n_microbatches=n_microbatches)

    def loss_fn(params, batch):
        B, S = batch["tokens"].shape
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        # same contract as the pp==1 branch: "mask" is the LOSS mask,
        # attention validity comes from "length" when provided (SFT
        # batches mask prompt tokens out of the loss but not attention)
        length = batch.get("length")
        if length is None:
            length = jnp.sum(batch["mask"], axis=-1)
        logits = fwd(params, cfg, batch["tokens"], positions,
                     kv_valid_len=length)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            logp, batch["targets"][..., None].astype(jnp.int32), axis=-1
        )[..., 0]
        mask = batch["mask"].astype(jnp.float32)
        return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)

    return loss_fn
