"""Fused on-device RAG admission (engine/rag_fusion.py).

The retrieve->assemble->prefill chain runs as one XLA program inside the
engine; these tests pin (a) the token-space prompt assembly against a
numpy reference, (b) end-to-end fused generation incl. on-device top-k
correctness, (c) the chain's auto-enable/fallback behavior.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from generativeaiexamples_tpu.embed.encoder import EmbeddingService
from generativeaiexamples_tpu.engine import Engine, EngineConfig, SamplingParams
from generativeaiexamples_tpu.engine.rag_fusion import (FusedRag,
                                                        FusedRagSpec,
                                                        build_prompt_parts,
                                                        corpus_rows)
from generativeaiexamples_tpu.models import encoder as enc_mod
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import ENCODER_TINY, LlamaConfig
from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer

CFG = LlamaConfig(vocab_size=320, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  max_position_embeddings=1024)
ENGINE_CFG = EngineConfig(max_slots=2, max_input_length=256,
                          max_output_length=32, prefill_buckets=(128, 256),
                          dtype="float32", kv_pool_tokens=1536,
                          page_size=64, steps_per_round=4)


def make_spec(**over):
    base = dict(prefix_ids=(1, 10, 11), sep_ids=(12,), mid_ids=(13, 14),
                suffix_ids=(15,), top_k=2, ctx_budget=40, bucket=128,
                chunk_tokens=16, q_bucket=16, enc_bucket=32)
    base.update(over)
    return FusedRagSpec(**base)


def make_encoder():
    params = enc_mod.init_params(ENCODER_TINY, jax.random.key(3),
                                 dtype=jnp.float32)
    return params, ENCODER_TINY


def encoder_qvec(enc_params, q_enc):
    hidden = enc_mod.apply(enc_params, ENCODER_TINY, q_enc[0][None],
                           q_enc[1][None])
    return np.asarray(enc_mod.mean_pool(hidden, q_enc[1][None],
                                        normalize=True)[0])


def pack_query(ids, bucket):
    q = np.zeros((2, bucket), np.int32)
    q[0, :len(ids)] = ids
    q[1, :len(ids)] = 1
    return jnp.asarray(q)


def reference_assembly(spec, doc_toks, doc_lens, order, q_ids):
    """Numpy mirror of FusedRag.assemble's layout rules."""
    out = list(spec.prefix_ids)
    budget = spec.ctx_budget
    used = 0
    for rank, i in enumerate(order):
        if doc_lens[i] == 0:
            continue
        cost = doc_lens[i] + (len(spec.sep_ids) if rank > 0 else 0)
        if used + cost > budget:
            break
        if rank > 0:
            out += list(spec.sep_ids)
        out += list(doc_toks[i][:doc_lens[i]])
        used += cost
    out += list(spec.mid_ids)
    out += list(q_ids)
    out += list(spec.suffix_ids)
    return out


def test_assemble_matches_reference():
    enc_params, enc_cfg = make_encoder()
    spec = make_spec()
    fused = FusedRag(enc_params, enc_cfg, spec)

    rng = np.random.default_rng(0)
    emb = rng.normal(size=(3, enc_cfg.hidden_size)).astype(np.float32)
    doc_toks = np.zeros((3, spec.chunk_tokens), np.int32)
    doc_lens = np.array([5, 16, 9], np.int32)
    for i in range(3):
        doc_toks[i, :doc_lens[i]] = 100 + 20 * i + np.arange(doc_lens[i])
    fused.set_corpus(emb, doc_toks, doc_lens)

    q_ids = [40, 41, 42]
    q_enc = pack_query([7, 8, 9], spec.enc_bucket)
    qvec = encoder_qvec(enc_params, q_enc)
    scores = emb @ qvec
    order = list(np.argsort(-scores)[:spec.top_k])

    tokens, length, top_ids = jax.jit(fused.assemble)(
        fused.enc_params, fused.corpus, q_enc,
        jnp.asarray(np.pad(q_ids, (0, spec.q_bucket - len(q_ids)))),
        jnp.int32(len(q_ids)))
    tokens = np.asarray(tokens)
    length = int(length)
    assert list(np.asarray(top_ids)) == order
    expected = reference_assembly(spec, doc_toks, doc_lens, order, q_ids)
    assert length == len(expected)
    np.testing.assert_array_equal(tokens[:length], expected)
    assert not tokens[length:].any()


def test_assemble_budget_cap():
    """Docs that blow the context budget are dropped, keeping the
    leading run (reference: LimitRetrievedNodesLength semantics)."""
    enc_params, enc_cfg = make_encoder()
    spec = make_spec(ctx_budget=18, top_k=3)
    fused = FusedRag(enc_params, enc_cfg, spec)
    emb = np.eye(3, enc_cfg.hidden_size, dtype=np.float32)
    doc_toks = np.tile(np.arange(16, dtype=np.int32), (3, 1))
    doc_lens = np.array([16, 16, 16], np.int32)
    fused.set_corpus(emb, doc_toks, doc_lens)
    q_enc = pack_query([5], spec.enc_bucket)
    tokens, length, top_ids = jax.jit(fused.assemble)(
        fused.enc_params, fused.corpus, q_enc,
        jnp.zeros((spec.q_bucket,), jnp.int32), jnp.int32(1))
    # only doc #1 fits (16 <= 18; adding sep+16 more would exceed)
    qvec = encoder_qvec(enc_params, q_enc)
    order = list(np.argsort(-(emb @ qvec))[:3])
    expected = reference_assembly(spec, doc_toks, doc_lens, order, [0])
    assert int(length) == len(expected)


def build_engine():
    params = llama.init_params(CFG, jax.random.key(0), dtype=jnp.float32)
    return Engine(params, CFG, ByteTokenizer(), ENGINE_CFG)


def test_fused_generation_end_to_end():
    enc_params, enc_cfg = make_encoder()
    eng = build_engine()
    spec = make_spec(bucket=128, q_bucket=16)
    eng.enable_fused_rag(enc_params, enc_cfg, spec)

    # corpus whose top hit is forced: doc 1's embedding IS the query's
    q_enc_ids = [7, 8, 9]
    q_enc = pack_query(q_enc_ids, spec.enc_bucket)
    qvec = encoder_qvec(enc_params, q_enc)
    rng = np.random.default_rng(1)
    emb = rng.normal(size=(4, enc_cfg.hidden_size)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True) * 5  # weak noise
    emb[1] = qvec
    toks = np.zeros((4, spec.chunk_tokens), np.int32)
    lens = np.full((4,), 6, np.int32)
    for i in range(4):
        toks[i, :6] = 50 + i
    eng.set_rag_corpus(emb, toks, lens)

    with eng:
        stream = eng.submit_rag([30, 31], q_enc_ids, SamplingParams(
            max_tokens=6, top_k=1, ignore_eos=True))
        stream.text()
    assert len(stream.token_ids) == 6
    assert stream.finish_reason == "length"
    assert len(stream.source_ids) == spec.top_k
    assert stream.source_ids[0] == 1     # on-device top-k found the match


def test_fused_and_plain_requests_coexist():
    enc_params, enc_cfg = make_encoder()
    eng = build_engine()
    spec = make_spec()
    eng.enable_fused_rag(enc_params, enc_cfg, spec)
    emb = np.eye(2, enc_cfg.hidden_size, dtype=np.float32)
    toks = np.ones((2, spec.chunk_tokens), np.int32)
    lens = np.full((2,), 4, np.int32)
    eng.set_rag_corpus(emb, toks, lens)
    with eng:
        s1 = eng.submit([5, 6, 7], SamplingParams(max_tokens=4, top_k=1,
                                                  ignore_eos=True))
        s2 = eng.submit_rag([30], [7], SamplingParams(max_tokens=4, top_k=1,
                                                      ignore_eos=True))
        s1.text()
        s2.text()
    assert len(s1.token_ids) == 4
    assert len(s2.token_ids) == 4


def test_corpus_regrow_recompiles():
    enc_params, enc_cfg = make_encoder()
    spec = make_spec()
    fused = FusedRag(enc_params, enc_cfg, spec)
    emb = np.eye(3, enc_cfg.hidden_size, dtype=np.float32)
    toks = np.ones((3, spec.chunk_tokens), np.int32)
    fused.set_corpus(emb, toks, np.full((3,), 2, np.int32))
    assert fused.corpus["emb"].shape[0] == 8      # pow2 capacity
    emb2 = np.eye(20, enc_cfg.hidden_size, dtype=np.float32)
    toks2 = np.ones((20, spec.chunk_tokens), np.int32)
    fused.set_corpus(emb2, toks2, np.full((20,), 2, np.int32))
    assert fused.corpus["emb"].shape[0] == 32
    assert int(fused.corpus["n"]) == 20


def test_chain_auto_enables_and_falls_back(tmp_path):
    """QAChatbot: fused turns on with an on-device embedder + engine LLM,
    stays off with the hash embedder, and still answers either way."""
    from generativeaiexamples_tpu.chains.examples.developer_rag import QAChatbot
    from generativeaiexamples_tpu.chains.llm import EngineLLM
    from generativeaiexamples_tpu.embed.encoder import HashEmbedder
    from generativeaiexamples_tpu.utils.app_config import AppConfig
    from generativeaiexamples_tpu.utils.configuration import from_dict

    cfg = from_dict(AppConfig, {
        "text_splitter": {"chunk_size": 24, "chunk_overlap": 4}})
    doc = tmp_path / "d.txt"
    doc.write_text("The MXU is a systolic array. " * 6)

    # host-path prompts are byte-tokenized here, so give these engines a
    # longer input ceiling than the fused-only fixtures
    chain_cfg = EngineConfig(max_slots=2, max_input_length=768,
                             max_output_length=32,
                             prefill_buckets=(128, 768), dtype="float32",
                             kv_pool_tokens=2048, page_size=64,
                             steps_per_round=4)

    def build_chain_engine():
        params = llama.init_params(CFG, jax.random.key(0),
                                   dtype=jnp.float32)
        return Engine(params, CFG, ByteTokenizer(), chain_cfg)

    enc_params, enc_cfg = make_encoder()
    embedder = EmbeddingService(enc_params, enc_cfg, ByteTokenizer(),
                                max_length=64, seq_buckets=(32, 64))
    eng = build_chain_engine()
    ex = QAChatbot(llm=EngineLLM(eng), embedder=embedder, config=cfg)
    ex.ingest_docs(str(doc), "d.txt")
    assert ex._fused_ready
    out = "".join(ex.rag_chain("What is the MXU?", 4))
    assert isinstance(out, str)
    # fused source attribution maps on-device rows back to documents
    assert ex.last_sources and ex.last_sources[0]["source"] == "d.txt"
    eng.stop()

    eng2 = build_chain_engine()
    ex2 = QAChatbot(llm=EngineLLM(eng2), embedder=HashEmbedder(),
                    config=cfg)
    ex2.ingest_docs(str(doc), "d.txt")
    assert not ex2._fused_ready          # hash embedder: host path
    out2 = "".join(ex2.rag_chain("What is the MXU?", 4))
    assert isinstance(out2, str)
    eng2.stop()
