"""Evaluation + data tooling (the reference's ``tools/`` tier).

``tools.eval`` is the script-form replacement for the reference's
4-notebook evaluation pipeline (reference: tools/evaluation/
01_synthetic_data_generation.ipynb -> 02_filling_RAG_outputs ->
03_eval_ragas.ipynb -> 04_Human_Like_RAG_Evaluation-AIP.ipynb):
synthetic QA generation from the knowledge base, RAG answer/context
filling, RAGAS-style faithfulness and context-precision, retrieval
nDCG/hit-rate/MRR, and an LLM-judge Likert loop — runnable headless in CI
(``python -m generativeaiexamples_tpu.tools.eval``) as well as against a
live serving stack.
"""
