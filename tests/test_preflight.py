"""tools/preflight.py: the consolidated contract gate — every check
green on a clean tree, and each check actually detects its failure
class (a preflight that can't fail protects nothing)."""

import json

import pytest

from tools import preflight


def test_all_checks_green():
    results = preflight.run_checks()
    assert set(results) == set(preflight.CHECKS)
    for name, errors in results.items():
        assert errors == [], f"{name}: {errors}"


def test_cli_exit_codes(capsys):
    assert preflight.main([]) == 0
    out = capsys.readouterr().out
    for name in preflight.CHECKS:
        assert f"ok   {name}" in out
    assert preflight.main(["--list"]) == 0


def test_cli_subset():
    assert preflight.main(["metrics-docs"]) == 0


def test_perf_gate_detects_regression(tmp_path):
    base = tmp_path / "base.json"
    cand = tmp_path / "cand.json"
    base.write_text(json.dumps({"decode_tokens_per_sec": 500.0,
                                "engine_p50_ttft_ms": 100.0}))
    cand.write_text(json.dumps({"decode_tokens_per_sec": 300.0,
                                "engine_p50_ttft_ms": 100.0}))
    errors = preflight.check_perf_gates(
        pairs=[(str(base), str(cand), {})])
    assert any("decode_tokens_per_sec" in e for e in errors)
    # missing artifacts are a loud failure, not a silent pass
    errors = preflight.check_perf_gates(
        pairs=[(str(tmp_path / "nope.json"), str(cand), {})])
    assert errors and "missing" in errors[0]


def test_disagg_check_detects_failure_classes():
    """The disagg check is green on the synthetic section and actually
    fails on each class of broken artifact — a disagg gate that can't
    fail would let the scenario silently measure unified twice."""
    import copy

    assert preflight.validate_disagg_block(
        preflight.synthetic_disagg()) == []
    # disagg arm without a prefill/decode split
    block = preflight.synthetic_disagg()
    block["arms"][1]["roles"] = {"decode": 2}
    assert any("prefill/decode" in e
               for e in preflight.validate_disagg_block(block))
    # unified arm that is secretly role-split
    block = preflight.synthetic_disagg()
    block["arms"][0]["roles"] = {"prefill": 1, "decode": 1}
    assert any("all-unified" in e
               for e in preflight.validate_disagg_block(block))
    # roles not summing to the chip count breaks equal-chips
    block = preflight.synthetic_disagg()
    block["arms"][1]["roles"] = {"prefill": 1, "decode": 2}
    assert any("equal-chips" in e
               for e in preflight.validate_disagg_block(block))
    # zero handoffs AND zero fallbacks: the two-leg path never ran
    block = preflight.synthetic_disagg()
    block["arms"][1]["handoffs"] = 0
    block["arms"][1]["fallbacks"] = 0
    assert any("measured" in e and "twice" in e
               for e in preflight.validate_disagg_block(block))
    # a missing arm kills the comparison outright
    block = preflight.synthetic_disagg()
    block["arms"] = [block["arms"][0]]
    assert any("missing the 'disagg' arm" in e
               for e in preflight.validate_disagg_block(block))
    # schema drift (field rename) is caught by the element-wise pass
    block = copy.deepcopy(preflight.synthetic_disagg())
    block["arms"][1]["goodput"] = block["arms"][1].pop("decode_goodput")
    assert any("disagg.arms[1]" in e
               for e in preflight.validate_disagg_block(block))


def test_obs_overhead_check_detects_failure_classes():
    """Green on the synthetic section, and each broken-artifact class
    actually fails — an overhead gate that can't fail would let the
    armed arm silently measure a disarmed stack twice."""
    assert preflight.validate_obs_overhead_block(
        preflight.synthetic_obs_overhead()) == []
    # the sampler never ran in the armed arm
    block = preflight.synthetic_obs_overhead()
    block["armed_samples"] = 0
    assert any("sampler never ran" in e
               for e in preflight.validate_obs_overhead_block(block))
    # the "armed" arm was actually disarmed
    block = preflight.synthetic_obs_overhead()
    block["history_interval_s"] = 0.0
    assert any("disarmed" in e
               for e in preflight.validate_obs_overhead_block(block))
    # headline number inconsistent with the arms it claims to compare
    block = preflight.synthetic_obs_overhead()
    block["overhead_pct"] = 40.0
    assert any("does not match the arms" in e
               for e in preflight.validate_obs_overhead_block(block))
    # an unmeasured arm
    block = preflight.synthetic_obs_overhead()
    block["disarmed_tokens_per_sec"] = 0.0
    assert any("positive rate" in e
               for e in preflight.validate_obs_overhead_block(block))
    # schema drift (field rename) caught by the element-wise pass
    block = preflight.synthetic_obs_overhead()
    block["tokens_per_sec_armed"] = block.pop("armed_tokens_per_sec")
    assert preflight.validate_obs_overhead_block(block)


def test_incident_bundle_validator_detects_failure_classes():
    """The synthetic bundle (built through the real history → alert →
    build_bundle pipeline) is green; each contract violation fails."""
    assert preflight.validate_incident_bundle(
        preflight.synthetic_incident_bundle()) == []
    # wrong schema tag
    bundle = preflight.synthetic_incident_bundle()
    bundle["schema"] = "incident/v0"
    assert any("schema" in e
               for e in preflight.validate_incident_bundle(bundle))
    # an alert-triggered bundle with no evidence: capture raced ahead
    # of evaluation
    bundle = preflight.synthetic_incident_bundle()
    bundle["trigger"]["evidence"] = {}
    assert any("no evidence" in e
               for e in preflight.validate_incident_bundle(bundle))
    # a bundle that froze nothing
    bundle = preflight.synthetic_incident_bundle()
    bundle["history"]["window"] = []
    assert any("froze nothing" in e
               for e in preflight.validate_incident_bundle(bundle))
    # a missing joined section
    bundle = preflight.synthetic_incident_bundle()
    del bundle["rounds"]
    assert any("'rounds'" in e
               for e in preflight.validate_incident_bundle(bundle))


def test_alerts_check_must_fire_leg_can_fail(monkeypatch):
    """Neuter gauge writes so the stall metric never climbs: the
    must-fire leg of the alerts check has to report it."""
    from generativeaiexamples_tpu.obs import metrics as obs_metrics

    monkeypatch.setattr(obs_metrics.Gauge, "set",
                        lambda self, value: None)
    errors = preflight.check_alerts()
    assert any("must-fire" in e for e in errors)


def test_alerts_check_must_resolve_leg_can_fail(monkeypatch):
    """Collapse the age-out sleep so the breach never leaves the rule
    window: the must-resolve leg has to report the stuck-firing rule."""
    import time

    monkeypatch.setattr(time, "sleep", lambda s: None)
    errors = preflight.check_alerts()
    assert any("must-resolve" in e for e in errors)


def test_metrics_docs_check_is_the_real_one(monkeypatch):
    """preflight's metrics-docs check is the same two-way checker the
    dedicated tier-1 test runs — doctor the doc text and it must
    fail."""
    from tools import check_metrics_docs as cmd
    with open(cmd.DOC_PATH) as f:
        text = f.read()
    broken = text.replace("`engine_requests`", "`engine_requestz`")
    errors = cmd.check(broken)
    assert any("engine_requests" in e for e in errors)
