"""Token-budget continuous scheduler: chunked-prefill / decode interleaving.

The prefill wall (BENCH_r05): e2e chat TTFT is 180 ms of which
``engine_first_readback`` is 173 ms — prefill IS the TTFT budget, and the
engine's former run-prefill-to-completion admission let one long prompt
monopolize the serve loop while every occupied decode slot starved. The
cure is the Sarathi/Orca recipe adapted to this engine's multi-step
rounds: plan each engine round as a MIX of decode steps for armed slots
plus prefill *chunks* for admitted requests, sized so the whole round
stays under a per-round token budget derived from a measured step-cost
model — decode keeps flowing at its usual cadence while long prefills
make page-quantized progress in the gaps.

Division of labor:

- **This module is pure host-side policy** — no jax, no device state, no
  engine internals. It converts (decode work this round, prefill jobs
  waiting) into a :class:`RoundPlan` under the budget, and orders
  admission by DEADLINE SLACK (requests whose deadline minus estimated
  prefill time is smallest go first; ties by arrival). That keeps every
  decision unit-testable without an engine.
- **The engine** (engine.py ``_plan_round``/``_execute_plan``) owns
  resources: it offers only what slots/pages allow, executes chunk
  dispatches, and keeps the PR-5 deadline semantics (queue-expired
  requests shed via ``deadline_queue`` before any page is touched).

Cost model: :class:`StepCostModel` loads the committed
``PROFILE_rNN.json`` roofline artifact (``tools/profile_decode.py
--json`` regenerates it per deployment, now including a measured
``prefill_ms_per_token``) and falls back to conservative defaults when
the artifact or a field is missing. The derived default budget is the
number of prefill tokens whose modeled cost equals ONE decode round —
i.e. piggybacked prefill can at most ~double a round's latency, the
stall-free-batching knee. ``SCHED_ROUND_BUDGET_TOKENS`` /
``SCHED_PREFILL_CHUNK_TOKENS`` (env or EngineConfig) override it per
deployment (docs/configuration.md).
"""

from __future__ import annotations

import glob
import json
import math
import os
import re
import threading
from dataclasses import dataclass, field, replace
from typing import Optional, Sequence

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def topology_key(mesh_shape: Optional[dict] = None) -> str:
    """Canonical topology label for cost-model rows: the mesh's
    non-trivial axes as ``axis=N`` pairs, sorted (``"tp=2"``,
    ``"sp=2,tp=2"``); a single chip — or a mesh of all-1 axes — is
    ``"tp=1"``. The same function labels ``tools/profile_decode.py
    --mesh`` artifacts and keys the engine's prior lookup, so the two
    can never drift apart. Takes a plain ``{axis: size}`` dict (this
    module stays jax-free): engines pass ``dict(mesh.shape)``."""
    if not mesh_shape:
        return "tp=1"
    parts = [f"{a}={int(s)}" for a, s in sorted(mesh_shape.items())
             if int(s) > 1]
    return ",".join(parts) if parts else "tp=1"


@dataclass(frozen=True)
class StepCostModel:
    """Per-deployment serving costs, in milliseconds.

    ``decode_step_ms`` is one fused decode step across ALL slots (the
    profile's ``full_ms_per_step``); ``prefill_ms_per_token`` is one
    prompt token through prefill. The ratio between them is what the
    budget derivation actually consumes: how many prefill tokens cost as
    much as a decode round.
    """

    decode_step_ms: float = 2.0
    prefill_ms_per_token: float = 0.125
    # One speculative-verification position (one token of a K+1-token
    # verify forward at decode occupancy, tools/profile_decode.py
    # ``verify_ms_per_token``). 0 = unmeasured: verification is then
    # priced 1:1 with prefill tokens (same forward math, the honest
    # default until the artifact carries the measurement).
    verify_ms_per_token: float = 0.0
    # KV-tier page migration (engine/kv_tier.py): milliseconds to move
    # one KV page host->device (restore) / device->host (offload).
    # 0 = unmeasured — the restore-vs-recompute decision then assumes
    # restore wins (on every real interconnect a page upload is far
    # cheaper than recomputing a page of prefill) until the online
    # calibrator has measured actual transfers.
    h2d_ms_per_page: float = 0.0
    d2h_ms_per_page: float = 0.0
    slots: int = 8
    source: str = "default"
    # The mesh shape these costs were measured at (``topology_key``
    # label; artifacts without one are single-chip measurements). A
    # tp-sharded engine must plan its FIRST rounds from the matching
    # row — a 2-chip decode step costs neither one chip's step nor
    # half of it, and the online calibrator only fixes the prior after
    # real traffic has already been (mis-)budgeted.
    topology: str = "tp=1"

    @classmethod
    def from_profile(cls, profile: dict, source: str = "profile",
                     topology: Optional[str] = None) -> "StepCostModel":
        decode = float(profile.get("full_ms_per_step") or 2.0)
        slots = int(profile.get("slots") or 8)
        prefill = profile.get("prefill_ms_per_token")
        if not prefill or prefill <= 0:
            # Older artifacts (≤ r06) predate the prefill measurement:
            # estimate a token's prefill cost from the decode step —
            # per-slot decode cost discounted by prefill's batching
            # efficiency (a whole bucket amortizes weight streaming the
            # way a decode step amortizes it over slots; 4x is the
            # conservative end of the measured 3-8x range).
            prefill = decode / max(1, slots) / 4.0
        verify = profile.get("verify_ms_per_token") or 0.0
        h2d = profile.get("h2d_ms_per_page") or 0.0
        d2h = profile.get("d2h_ms_per_page") or 0.0
        return cls(decode_step_ms=decode,
                   prefill_ms_per_token=float(prefill),
                   verify_ms_per_token=float(verify),
                   h2d_ms_per_page=float(h2d),
                   d2h_ms_per_page=float(d2h),
                   slots=slots, source=source,
                   topology=str(topology or profile.get("topology")
                                or "tp=1"))

    @classmethod
    def _from_artifact(cls, profile: dict, topology: Optional[str],
                       source: str) -> Optional["StepCostModel"]:
        """One artifact's topology-matched model, or None when it has no
        row for the requested topology. Artifacts carry their own
        ``topology`` label (absent == single-chip ``tp=1``) and may
        carry a ``topologies`` dict of per-mesh rows (each row's keys
        override the artifact's shared fields) — one sweep run can
        serve every rung."""
        own = str(profile.get("topology") or "tp=1")
        if topology is None or topology == own:
            return cls.from_profile(profile, source=source, topology=own)
        rows = profile.get("topologies")
        if isinstance(rows, dict) and isinstance(rows.get(topology),
                                                 dict):
            merged = {k: v for k, v in profile.items()
                      if k != "topologies"}
            merged.update(rows[topology])
            return cls.from_profile(merged,
                                    source=f"{source}@{topology}",
                                    topology=topology)
        return None

    @classmethod
    def load(cls, path: Optional[str] = None,
             topology: Optional[str] = None) -> "StepCostModel":
        """Resolve the deployment's cost model: explicit ``path``, else
        ``SCHED_PROFILE_JSON``, else the newest committed
        ``PROFILE_rNN.json`` at the repo root, else defaults. A missing
        or malformed artifact degrades silently to defaults — the
        scheduler must never keep an engine from building.

        ``topology``: the engine's mesh label (:func:`topology_key`).
        Precedence per docs/scheduler.md: an artifact whose own label or
        ``topologies`` row matches wins; with NO matching row anywhere,
        the newest parseable artifact is used as-is (its ``topology``
        field then records the mismatch) — a wrong-but-measured prior
        beats built-in defaults, and the online calibrator converges it."""
        candidates = []
        if path:
            candidates.append(path)
        env = os.environ.get("SCHED_PROFILE_JSON", "")
        if env:
            candidates.append(env)
        def _round_no(p: str) -> int:
            m = re.search(r"_r(\d+)\.json$", p)
            return int(m.group(1)) if m else -1
        # Numeric sort on the round number — lexicographic would pick
        # r99 over r100 (and r9 over r10) the day rounds grow a digit.
        candidates.extend(sorted(
            glob.glob(os.path.join(_REPO_ROOT, "PROFILE_r*.json")),
            key=_round_no, reverse=True))
        fallback: Optional["StepCostModel"] = None
        for cand in candidates:
            # Catch the full malformed-artifact surface, not just parse
            # errors: valid JSON that isn't an object of numbers (`[]`,
            # `{"prefill_ms_per_token": "fast"}`) raises Attribute/Type
            # errors out of from_profile — the fallback contract above
            # covers those the same as a missing file.
            try:
                with open(cand) as f:
                    profile = json.load(f)
                model = cls._from_artifact(profile, topology,
                                           os.path.basename(cand))
                if model is not None:
                    return model
                if fallback is None:
                    fallback = cls.from_profile(
                        profile, source=os.path.basename(cand))
            except (OSError, ValueError, TypeError, AttributeError,
                    KeyError):
                continue
        return fallback if fallback is not None else cls()

    def prefill_s(self, tokens: int) -> float:
        """Modeled wall seconds to prefill ``tokens`` prompt tokens."""
        return max(0, tokens) * self.prefill_ms_per_token / 1e3

    def decode_round_ms(self, steps: int) -> float:
        return steps * self.decode_step_ms

    def verify_cost_tokens(self, positions: int) -> int:
        """Price a speculative verify round against the token budget:
        ``positions`` scored positions (slots x S), converted to
        prefill-token units through the measured per-token costs. With
        no verify measurement the ratio is 1 — a verified position and
        a prefill token run the same multi-token forward math, so 1:1
        is the honest default rather than an optimistic discount."""
        if positions <= 0:
            return 0
        if self.verify_ms_per_token <= 0 or self.prefill_ms_per_token <= 0:
            return positions
        return max(1, math.ceil(
            positions * self.verify_ms_per_token
            / self.prefill_ms_per_token))

    def restore_ms(self, pages: int) -> float:
        """Modeled wall ms to restore ``pages`` KV pages host->device."""
        return max(0, pages) * self.h2d_ms_per_page

    def restore_cheaper(self, pages: int, page_size: int) -> bool:
        """The KV-tier admission decision: is restoring ``pages`` pages
        from host RAM priced cheaper than recomputing their tokens
        through prefill? Unmeasured H2D (0) answers True — restore is
        assumed to win until the online calibrator has real transfer
        measurements; once it does, the comparison is honest per
        deployment (engine counts the refusals as
        ``kv_restore_skipped_cost``)."""
        if pages <= 0:
            return False
        if self.h2d_ms_per_page <= 0:
            return True
        return self.restore_ms(pages) \
            < pages * page_size * self.prefill_ms_per_token

    def handoff_cheaper(self, pages: int, page_size: int) -> bool:
        """The disaggregation pricing rule: is shipping ``pages``
        finished prefix pages donor-device → host → wire → host →
        decode-device priced cheaper than the decode replica recomputing
        their tokens through prefill? The handoff pays BOTH transfer
        legs (``d2h`` on the donor, ``h2d`` on the receiver); unmeasured
        legs (0) answer True, mirroring :meth:`restore_cheaper` — the
        handoff is assumed to win until the calibrator has real
        transfer measurements."""
        if pages <= 0:
            return False
        per_page = self.d2h_ms_per_page + self.h2d_ms_per_page
        if per_page <= 0:
            return True
        return pages * per_page \
            < pages * page_size * self.prefill_ms_per_token


def derive_round_budget(model: StepCostModel, steps_per_round: int,
                        page_size: int) -> int:
    """Default per-round prefill-token budget: the tokens whose modeled
    prefill cost equals one full decode round. At that size a round that
    piggybacks a chunk takes at most ~2x a pure decode round — decoding
    streams keep flowing while prefill makes real progress. Quantized to
    whole pages (chunks scatter KV page-wise); floored at one page so a
    pathological cost model can never stall admission."""
    tokens = model.decode_round_ms(steps_per_round) / model.prefill_ms_per_token
    pages = max(1, int(tokens) // page_size)
    return pages * page_size


def online_calib_enabled(default: bool = True) -> bool:
    """``SCHED_ONLINE_CALIB`` gate for the online cost calibrator:
    ``0``/``false`` pins the static (artifact/env/default) model; any
    other value — and the unset default — enables calibration."""
    raw = os.environ.get("SCHED_ONLINE_CALIB", "").strip().lower()
    if not raw:
        return default
    return raw not in ("0", "false", "no", "off")


class OnlineCalibrator:
    """EWMA calibration of :class:`StepCostModel` from measured rounds.

    The committed ``PROFILE_rNN.json`` prior is a point measurement from
    whatever machine ran the profiler — the ROADMAP repeatedly flags the
    CPU-labeled artifacts as "regenerate on chip". This class closes the
    loop instead: the engine feeds it each completed round's *measured*
    per-token costs (round telemetry, ``obs/rounds.py``), it keeps an
    exponentially weighted moving average per cost component, and
    :meth:`current` returns the model the scheduler should plan with —
    the PRIOR blended toward the EWMA on a linear ramp
    (``weight = min(1, n / warmup)``): the first observations only
    nudge the model, and after ``warmup`` samples the measurement is
    fully trusted (the EWMA itself keeps absorbing noise) — a badly
    wrong artifact prior is fully displaced within a handful of rounds
    instead of lingering as a 1/n tail.

    Only *pure* rounds are attributable: a decode-only round measures
    ``decode_step_ms``, a prefill-only round ``prefill_ms_per_token``, a
    verify-only round ``verify_ms_per_token``. Mixed rounds are skipped
    (their time cannot be split honestly) — under real traffic pure
    rounds of every kind occur constantly, so the calibrator still sees
    a steady diet.

    Thread contract: ``observe_*`` run on the engine's harvest thread,
    ``current``/``drift`` on the scheduler thread (and scrapes); a small
    lock keeps each update atomic and the cached blended model
    consistent.
    """

    def __init__(self, prior: StepCostModel, *, alpha: float = 0.25,
                 warmup: int = 4):
        self.prior = prior
        self.alpha = float(alpha)
        self.warmup = max(1, int(warmup))
        self._lock = threading.Lock()
        self._ewma: dict[str, float] = {}
        self._n: dict[str, int] = {}
        self._cached: StepCostModel = prior
        self._dirty = False
        self.version = 0    # bumps per observation; recalibrate() keys off it

    def _observe(self, key: str, value: float) -> None:
        if value <= 0 or not math.isfinite(value):
            return
        with self._lock:
            prev = self._ewma.get(key)
            self._ewma[key] = (value if prev is None
                               else prev + self.alpha * (value - prev))
            self._n[key] = self._n.get(key, 0) + 1
            self._dirty = True
            self.version += 1

    def observe_decode(self, steps: int, device_ms: float) -> None:
        """A pure decode round of ``steps`` fused steps took
        ``device_ms`` of device time."""
        if steps > 0:
            self._observe("decode_step_ms", device_ms / steps)

    def observe_prefill(self, tokens: int, device_ms: float) -> None:
        """A prefill-only round computed ``tokens`` prompt tokens."""
        if tokens > 0:
            self._observe("prefill_ms_per_token", device_ms / tokens)

    def observe_verify(self, positions: int, device_ms: float) -> None:
        """A verify-only round scored ``positions`` slot-positions."""
        if positions > 0:
            self._observe("verify_ms_per_token", device_ms / positions)

    def observe_h2d(self, pages: int, wall_ms: float) -> None:
        """A KV-tier restore uploaded ``pages`` pages host->device
        (engine-measured dispatch wall — the restore pricing input)."""
        if pages > 0:
            self._observe("h2d_ms_per_page", wall_ms / pages)

    def observe_d2h(self, pages: int, wall_ms: float) -> None:
        """A KV-tier offload read ``pages`` pages back device->host
        (harvest-measured readback wait)."""
        if pages > 0:
            self._observe("d2h_ms_per_page", wall_ms / pages)

    def _blend(self, key: str, prior_value: float) -> float:
        ewma = self._ewma.get(key)
        if ewma is None:
            return prior_value
        w = min(1.0, self._n.get(key, 0) / self.warmup)
        return (1.0 - w) * prior_value + w * ewma

    def samples(self, key: str) -> int:
        with self._lock:
            return self._n.get(key, 0)

    def current(self) -> StepCostModel:
        """The blended model (cached; rebuilt only after new
        observations). Falls back to the prior field-by-field until a
        component has evidence."""
        with self._lock:
            if not self._dirty:
                return self._cached
            self._cached = replace(
                self.prior,
                decode_step_ms=self._blend("decode_step_ms",
                                           self.prior.decode_step_ms),
                prefill_ms_per_token=self._blend(
                    "prefill_ms_per_token",
                    self.prior.prefill_ms_per_token),
                verify_ms_per_token=self._blend(
                    "verify_ms_per_token",
                    self.prior.verify_ms_per_token),
                h2d_ms_per_page=self._blend(
                    "h2d_ms_per_page", self.prior.h2d_ms_per_page),
                d2h_ms_per_page=self._blend(
                    "d2h_ms_per_page", self.prior.d2h_ms_per_page),
                source=self.prior.source + "+online")
            self._dirty = False
            return self._cached


@dataclass
class PrefillJob:
    """One prefill the scheduler may advance this round.

    ``key`` is an opaque handle (the engine's ``_Request``) echoed back
    in the plan. ``remaining`` counts tokens still to COMPUTE: the
    prompt minus everything already prefilled minus any prefix-cache hit
    — a warm request's chunk plan shrinks by exactly its cached prefix
    (the PR-1 interaction; see docs/scheduler.md)."""

    key: object
    remaining: int
    deadline_t: Optional[float] = None
    seq: int = 0
    started: bool = False    # already holds a slot (in-flight chunks)


@dataclass
class RoundPlan:
    """One engine round: the decode dispatch (steps and how many armed
    slots ride it) plus the prefill chunks that fit under the budget."""

    decode_steps: int
    active_decodes: int
    chunks: list = field(default_factory=list)  # [(key, grant_tokens)]
    budget_tokens: int = 0
    # Explicit decode-work price for rounds whose cost is NOT steps x
    # slots — a speculative verify round scores S positions per slot in
    # one step (engine passes StepCostModel.verify_cost_tokens). None =
    # the classic normalization below.
    decode_cost_override: Optional[int] = None

    @property
    def decode_cost_tokens(self) -> int:
        if not self.decode_steps:
            return 0
        if self.decode_cost_override is not None:
            return self.decode_cost_override
        return self.decode_steps * max(1, self.active_decodes)

    @property
    def prefill_tokens(self) -> int:
        return sum(n for _, n in self.chunks)

    @property
    def interleaved(self) -> bool:
        return bool(self.decode_steps and self.chunks)


class TokenBudgetScheduler:
    """Plans rounds under a token budget; orders admission by slack.

    Token units: one prefill token costs 1; one decode step costs one
    token PER ACTIVE SLOT (each armed slot emits a token per step — the
    same normalization Sarathi/vLLM budgets use, and it makes the
    budget directly comparable to ``tokens_generated``).
    """

    def __init__(self, cost: StepCostModel, *, page_size: int,
                 steps_per_round: int,
                 round_budget_tokens: Optional[int] = None,
                 chunk_tokens: Optional[int] = None,
                 max_one_shot_tokens: Optional[int] = None,
                 calibrator: Optional[OnlineCalibrator] = None):
        self._static_cost = cost
        # Online calibration (``OnlineCalibrator``): when installed, the
        # scheduler plans with the measured-blended model instead of the
        # static artifact prior, and ``recalibrate()`` periodically
        # re-derives the round budget from it. Precedence (see
        # docs/scheduler.md): explicit env/config budget overrides are
        # PINNED — calibration then only refines slack estimates and
        # verify pricing, never the operator's chosen budget.
        self.calibrator = calibrator
        self._budget_pinned = round_budget_tokens is not None
        self._chunk_pinned = chunk_tokens is not None
        self.page_size = page_size
        self.steps_per_round = steps_per_round
        if round_budget_tokens is not None:
            budget = max(page_size, int(round_budget_tokens))
        else:
            budget = derive_round_budget(cost, steps_per_round, page_size)
        self.round_budget_tokens = budget
        # Per-chunk cap: a single request's grant within one round.
        # Defaults to the whole budget (the budget is already the round
        # latency bound); the knob exists to force finer interleaving.
        self.chunk_tokens = max(page_size, int(chunk_tokens)) \
            if chunk_tokens else budget
        # Above this, a request is never one-shot even on an idle engine
        # (the engine passes its largest prefill bucket).
        self.max_one_shot_tokens = max_one_shot_tokens
        if max_one_shot_tokens is not None:
            # The bucket is also the largest single DISPATCH the engine
            # can execute: a grant beyond it would deduct budget for
            # tokens _advance_prefill clamps away — planned work that
            # evaporates instead of going to other waiting prefills.
            self.chunk_tokens = min(self.chunk_tokens,
                                    max(page_size, max_one_shot_tokens))
        # Fair-rotation cursor: when the leftover is too small for every
        # job to get a page (the 1-page default budget is the common
        # case), WHO gets this round's page rotates across rounds so a
        # waiting job's admission is bounded by ~len(jobs) rounds.
        self._rr = 0
        self._calib_version = -1   # last calibrator version recalibrated at

    @property
    def cost(self) -> StepCostModel:
        """The model rounds are planned with: the calibrator's blended
        model when online calibration is on, the static artifact/env
        model otherwise."""
        if self.calibrator is not None:
            return self.calibrator.current()
        return self._static_cost

    def recalibrate(self) -> bool:
        """Re-derive the round budget from the current (blended) cost
        model. Called from the engine's scheduler thread between rounds;
        cheap no-op unless the calibrator saw new evidence since the
        last call. Explicitly pinned budgets (env/config) never move.
        Returns True when the budget actually changed."""
        if self.calibrator is None or self._budget_pinned:
            return False
        version = self.calibrator.version
        if version == self._calib_version:
            return False
        self._calib_version = version
        budget = derive_round_budget(self.cost, self.steps_per_round,
                                     self.page_size)
        if budget == self.round_budget_tokens:
            return False
        self.round_budget_tokens = budget
        if not self._chunk_pinned:
            # The chunk cap follows the budget (its documented default),
            # still clamped to the largest dispatchable bucket.
            cap = budget
            if self.max_one_shot_tokens is not None:
                cap = min(cap, max(self.page_size,
                                   self.max_one_shot_tokens))
            self.chunk_tokens = cap
        return True

    # ------------------------------------------------------------ slack

    def slack_s(self, job: PrefillJob, now: float) -> float:
        """Deadline slack: seconds to spare if this job's prefill
        started NOW — (deadline - now) minus its modeled prefill time.
        No deadline → +inf (deadline'd traffic goes first; among
        unconstrained requests arrival order holds)."""
        if job.deadline_t is None:
            return math.inf
        return (job.deadline_t - now) - self.cost.prefill_s(job.remaining)

    def order(self, jobs: Sequence[PrefillJob], now: float
              ) -> list[PrefillJob]:
        """Admission order: smallest slack first, arrival order as the
        tiebreak (and the total order for no-deadline traffic). The
        engine sheds queue-EXPIRED requests before offering jobs here
        (PR-5 ``deadline_queue``); negative-slack-but-unexpired jobs
        sort first — their only chance of meeting the deadline is
        starting immediately."""
        return sorted(jobs, key=lambda j: (self.slack_s(j, now), j.seq))

    # ------------------------------------------------------------- plan

    def plan_round(self, *, decode_steps: int, active_decodes: int,
                   inflight: Sequence[PrefillJob] = (),
                   backlog: Sequence[PrefillJob] = (),
                   now: float = 0.0,
                   max_new: Optional[int] = None,
                   decode_cost_tokens: Optional[int] = None) -> RoundPlan:
        """Pack one round: decode first (decode is NEVER displaced —
        stall-free batching means ongoing generations keep their
        cadence), then prefill chunks into the leftover budget.

        ``inflight`` jobs (mid-prefill, already holding a slot) advance
        before new admissions — arming a half-done slot frees budget
        sooner than starting another prompt. ``backlog`` jobs are
        admission candidates ordered by slack here; ``max_new`` caps how
        many of them (slack-order first) may be granted this round — the
        engine passes its free-slot count, so budget is never split
        across jobs that cannot start and then wasted when the executor
        runs out of slots. ``decode_cost_tokens`` overrides the classic
        steps x slots decode price for rounds whose work is shaped
        differently — a speculative verify round scores S positions per
        slot in one step (StepCostModel.verify_cost_tokens).

        Grants are whole pages except a job's FINAL grant (the engine's
        final-chunk program takes any tail length). Two liveness
        guarantees: if prefill work exists, at least one page is granted
        even when decode consumed the whole budget (a saturated decode
        fleet must not starve admission forever), and on an IDLE engine
        (nothing decoding, nothing else waiting) a lone job up to 2x the
        round budget (and never past ``max_one_shot_tokens``, the
        largest compiled bucket) is granted whole — chunking a typical
        prompt would tax its TTFT with extra dispatches while protecting
        nobody, but an UNBOUNDED one-shot is un-preemptible once
        dispatched and would re-open the prefill wall for a request
        arriving moments later.
        """
        plan = RoundPlan(decode_steps=decode_steps,
                         active_decodes=active_decodes,
                         budget_tokens=self.round_budget_tokens,
                         decode_cost_override=decode_cost_tokens)
        admitted = self.order(backlog, now)
        if max_new is not None:
            admitted = admitted[:max(0, max_new)]
        jobs = list(inflight) + admitted
        if not jobs:
            return plan
        page = self.page_size
        leftover = self.round_budget_tokens - plan.decode_cost_tokens
        # Liveness floor: decode saturation may never starve prefill.
        leftover = max(leftover, page)
        # Idle engine, one waiter: whole-prompt grant (see docstring) —
        # but only up to 2x the round budget (and never past the largest
        # compiled bucket). A dispatched grant is un-preemptible, so an
        # unbounded one-shot would re-open the prefill wall for whoever
        # arrives a microsecond later: a lone 3072-token prompt would
        # monopolize the device for its whole prefill. 2x the budget
        # keeps the lone-prompt fast path for typical prompts while
        # bounding any later arrival's wait to ~2 extra round-times.
        one_shot_cap = 2 * self.round_budget_tokens
        if self.max_one_shot_tokens is not None:
            one_shot_cap = min(one_shot_cap, self.max_one_shot_tokens)
        if (decode_steps == 0 and active_decodes == 0 and len(jobs) == 1
                and not jobs[0].started
                and jobs[0].remaining <= one_shot_cap):
            plan.chunks.append((jobs[0].key, jobs[0].remaining))
            return plan
        # Two-phase packing. Phase 1 hands every job a FAIR SHARE of the
        # leftover (page-quantized, one page minimum): a short prompt
        # behind a long in-flight prefill admits THIS round instead of
        # waiting out the whole long prefill — strict priority order
        # would starve it, which is the head-of-line blocking this
        # scheduler exists to kill. Phase 2 re-grants whatever the
        # fair pass left unused (jobs smaller than their share) to the
        # highest-priority jobs so no budget is wasted.
        share = max(page, (leftover // len(jobs)) // page * page)
        # Scarcity rotation: when the leftover can't give every job a
        # page (e.g. the 1-page default budget), a fixed packing order
        # would hand the SAME job the page every round — strict
        # head-of-line blocking in fair-share clothing. Rotating who
        # packs first across rounds bounds any job's wait for its next
        # page to ~len(jobs) rounds.
        order_idx = list(range(len(jobs)))
        if leftover < page * len(jobs):
            start = self._rr % len(jobs)
            order_idx = order_idx[start:] + order_idx[:start]
        self._rr += 1
        granted: dict[int, int] = {}      # job index -> raw tokens
        for phase_cap in (share, None):
            for i in order_idx:
                job = jobs[i]
                if leftover <= 0:
                    break
                cap = leftover if phase_cap is None else phase_cap
                grant = min(job.remaining - granted.get(i, 0),
                            self.chunk_tokens - granted.get(i, 0),
                            cap, leftover)
                if grant < job.remaining - granted.get(i, 0):
                    grant = (grant // page) * page
                if grant <= 0:
                    continue
                granted[i] = granted.get(i, 0) + grant
                leftover -= grant
        for i, job in enumerate(jobs):
            total = granted.get(i, 0)
            if total <= 0:
                continue
            if total < job.remaining:
                # Non-final grant: quantize DOWN to whole pages so every
                # later chunk starts page-aligned (chunk KV scatters
                # page-wise; a ragged boundary would split a page across
                # two dispatches).
                total = (total // page) * page
                if total <= 0:
                    continue
            plan.chunks.append((job.key, total))
        return plan
