"""DocumentIndex: embedder + vector store + text/metadata in one object.

The working unit the chain server ingests into and retrieves from — the
role LlamaIndex's ``VectorStoreIndex`` / LangChain's vectorstore wrappers
play in the reference (reference: common/utils.py:143-229,
examples/developer_rag/chains.py:77-80 ``insert_nodes``).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import numpy as np

from ..utils import faults
from ..utils.errors import RetrievalError
from ..utils.resilience import get_breaker
from .store import VectorStore, get_vector_store


@dataclass
class Document:
    """A retrievable chunk: text + metadata (+ score when returned)."""
    text: str
    metadata: dict[str, Any] = field(default_factory=dict)
    id: Optional[int] = None
    score: Optional[float] = None


class DocumentIndex:
    def __init__(self, embedder, store: Optional[VectorStore] = None,
                 store_name: str = "exact"):
        self.embedder = embedder
        self.store = store or get_vector_store(store_name, dim=embedder.dim)
        self._docs: dict[int, Document] = {}

    def __len__(self) -> int:
        return len(self.store)

    def add_documents(self, docs: Sequence[Document]) -> list[int]:
        if not docs:
            return []
        emb = self.embedder.embed_documents([d.text for d in docs])
        ids = self.store.add(np.asarray(emb, np.float32))
        for i, doc in zip(ids, docs):
            doc.id = i
            self._docs[i] = doc
        return ids

    def add_texts(self, texts: Sequence[str],
                  metadatas: Optional[Sequence[dict]] = None) -> list[int]:
        metadatas = metadatas or [{} for _ in texts]
        return self.add_documents(
            [Document(text=t, metadata=dict(m))
             for t, m in zip(texts, metadatas)])

    def similarity_search(self, query: str, k: int = 4) -> list[Document]:
        """Top-k documents for a text query (embedder's query mode).

        Both external dependencies — the embedder and the vector store —
        run under named circuit breakers (utils/resilience.py): after
        repeated failures the breaker opens and this raises
        ``BreakerOpenError`` in microseconds instead of stalling on a
        dead backend. Raw backend exceptions (a down Milvus, a pgvector
        connection reset, an injected fault) are wrapped in
        ``RetrievalError`` with ``reason`` set to the failing dependency,
        so chains can degrade to their LLM-only path and label the
        fallback. ``BreakerOpenError`` passes through untouched (it
        already carries the breaker name)."""
        from ..obs.tracing import event_span
        from ..utils.errors import BreakerOpenError

        def _embed():
            faults.inject("embed")
            return np.asarray(self.embedder.embed_query(query), np.float32)

        def _search(q):
            faults.inject("retrieval.search")
            return self.store.search(q, k=k)

        if len(self.store) == 0:
            return []
        try:
            with event_span("embedding", mode="query", chars=len(query)):
                q = get_breaker("embed").call(_embed)
        except BreakerOpenError:
            raise
        except Exception as exc:  # noqa: BLE001 — typed for degradation
            raise RetrievalError(f"query embedding failed: {exc}",
                                 reason="embed") from exc
        try:
            hits = get_breaker("retrieval").call(_search, q)[0]
        except BreakerOpenError:
            raise
        except Exception as exc:  # noqa: BLE001 — typed for degradation
            raise RetrievalError(f"vector search failed: {exc}",
                                 reason="retrieval") from exc
        out = []
        for hit in hits:
            doc = self._docs.get(hit.id)
            if doc is not None:
                out.append(Document(text=doc.text, metadata=doc.metadata,
                                    id=hit.id, score=hit.score))
        return out

    def get(self, doc_id: int):
        """The stored Document for an id, or None."""
        return self._docs.get(doc_id)

    def export_corpus(self):
        """(ids, embeddings (N, D), texts) of every live document — the
        feed for the engine's device-resident fused-RAG corpus. None when
        the backing store can't expose raw vectors (external servers)."""
        export = getattr(self.store, "export_vectors", None)
        if export is None:
            return None
        ids, emb = export()
        keep = [(i, row) for i, row in zip(ids, emb) if i in self._docs]
        if not keep:
            return [], np.zeros((0, self.embedder.dim), np.float32), []
        ids = [i for i, _ in keep]
        emb = np.stack([row for _, row in keep])
        return ids, emb, [self._docs[i].text for i in ids]

    def delete(self, ids: Sequence[int]) -> None:
        self.store.delete(ids)
        for i in ids:
            self._docs.pop(i, None)

    def sources(self) -> list[str]:
        """Distinct source filenames across the index (for the KB page;
        reference: frontend kb.py file table)."""
        names = {d.metadata.get("source", "") for d in self._docs.values()}
        return sorted(n for n in names if n)

    # ---------------------------------------------------------- persistence

    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        self.store.save(os.path.join(path, "store"))
        with open(os.path.join(path, "docs.jsonl"), "w") as f:
            for i, doc in sorted(self._docs.items()):
                f.write(json.dumps(
                    {"id": i, "text": doc.text, "metadata": doc.metadata}) + "\n")

    def load_docs(self, path: str) -> None:
        """Restore texts/metadata; the store is reloaded by its own class."""
        with open(os.path.join(path, "docs.jsonl")) as f:
            for line in f:
                rec = json.loads(line)
                self._docs[rec["id"]] = Document(
                    text=rec["text"], metadata=rec["metadata"], id=rec["id"])
