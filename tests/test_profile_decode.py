"""Smoke for tools/profile_decode.py --json: the roofline-attribution
artifact (PROFILE_rNN.json round record) must be written with a stable
key set, on any backend — the driver diffs these fields round over
round, so a rename here is as breaking as a bench-field rename.

Two artifact shapes are pinned: the classic single-rung attribution and
the ``--slots A,B,...`` sweep (one attribution entry per slot rung plus
per-rung achieved-bandwidth fraction — the BENCH_SWEEP ladder as one
command)."""

import json
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))


SHARED_KEYS = {
    "tool", "model", "device", "platform", "quant", "kv_quant",
    "steps_per_round", "page_size", "param_gb",
    "matmul_floor_ms_per_step",
    # step-cost model inputs for the token-budget scheduler
    "prefill_bucket_tokens", "prefill_ms_per_token",
    # geometry of the per-rung speculative-verify measurement (the S in
    # each rung's S-position verify_ms_per_step)
    "verify_positions",
    # topology row key (engine/scheduler.py topology_key): which mesh
    # shape these costs were measured at ("tp=1" = single chip)
    "topology", "mesh_devices",
}

RUNG_KEYS = {
    "slots", "window_pages", "live_pages", "kv_live_bytes",
    "full_ms_per_step", "no_unembed_ms_per_step", "window1_ms_per_step",
    "unembed_ms_per_step", "window_stream_ms_per_step", "tokens_per_sec",
    # roofline: must-move bytes over measured step time vs chip peak
    "achieved_bw_gbps", "achieved_bw_fraction",
    # speculative verify step at this occupancy (StepCostModel pricing)
    "verify_ms_per_step", "verify_ms_per_token",
}

REQUIRED_KEYS = SHARED_KEYS | RUNG_KEYS

# Sweep shape: shared keys + the rung list + the StepCostModel mirror
# keys (engine/scheduler.py reads full_ms_per_step/slots/
# prefill_ms_per_token/verify_ms_per_token at TOP level, so a sweep
# artifact committed as the newest PROFILE_rNN still feeds the
# scheduler's cost model).
SWEEP_KEYS = SHARED_KEYS | {"slots_sweep", "rungs", "slots",
                            "full_ms_per_step", "verify_ms_per_token"}


def _setenv(monkeypatch):
    monkeypatch.setenv("PROF_MODEL", "llama-tiny")
    monkeypatch.setenv("PROF_QUANT", "none")
    monkeypatch.setenv("PROF_SLOTS", "2")
    monkeypatch.setenv("PROF_WINDOW", "2")
    monkeypatch.setenv("PROF_STEPS", "4")


def test_profile_decode_json_artifact(tmp_path, monkeypatch):
    import profile_decode

    _setenv(monkeypatch)
    path = str(tmp_path / "PROFILE_test.json")
    artifact = profile_decode.main(json_path=path)
    assert os.path.exists(path)
    with open(path) as f:
        on_disk = json.load(f)
    assert on_disk == artifact
    assert set(on_disk) == REQUIRED_KEYS
    assert on_disk["tool"] == "profile_decode"
    assert on_disk["full_ms_per_step"] > 0
    assert 0 <= on_disk["achieved_bw_fraction"] <= 1.5
    # attribution decomposes the full round: ablations can't be slower
    # than the full program by more than noise (CPU timing is jittery;
    # the bound only catches sign/unit bugs)
    assert on_disk["unembed_ms_per_step"] > -10.0
    assert on_disk["window_stream_ms_per_step"] > -10.0


def test_profile_decode_slots_sweep_artifact(tmp_path, monkeypatch):
    """--slots A,B writes ONE artifact with per-rung attribution +
    achieved-bandwidth fraction, and mirrors the first rung's cost-model
    keys at top level (StepCostModel.from_profile contract)."""
    import profile_decode

    from generativeaiexamples_tpu.engine.scheduler import StepCostModel

    _setenv(monkeypatch)
    path = str(tmp_path / "PROFILE_sweep.json")
    artifact = profile_decode.main(json_path=path, slots_arg="1,2")
    with open(path) as f:
        on_disk = json.load(f)
    assert on_disk == artifact
    assert set(on_disk) == SWEEP_KEYS
    assert on_disk["slots_sweep"] == [1, 2]
    assert [r["slots"] for r in on_disk["rungs"]] == [1, 2]
    for rung in on_disk["rungs"]:
        assert set(rung) == RUNG_KEYS
        assert rung["full_ms_per_step"] > 0
        assert 0 <= rung["achieved_bw_fraction"] <= 1.5
    # top-level mirror == first rung (the scheduler's cost model reads
    # these without knowing about sweeps)
    assert on_disk["slots"] == on_disk["rungs"][0]["slots"]
    assert (on_disk["full_ms_per_step"]
            == on_disk["rungs"][0]["full_ms_per_step"])
    model = StepCostModel.from_profile(on_disk, source=path)
    assert model.decode_step_ms == on_disk["full_ms_per_step"]
    assert model.verify_ms_per_token == on_disk["verify_ms_per_token"]
    # verify pricing: ratio of the measured per-token costs, ceil'd
    assert model.verify_cost_tokens(0) == 0
    assert model.verify_cost_tokens(16) >= 1


def test_committed_round_artifact_is_valid():
    """The committed PROFILE_rNN.json next to BENCH parses and carries
    the current contract, whichever shape (single-rung or sweep) the
    round used."""
    import glob
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    artifacts = sorted(glob.glob(os.path.join(root, "PROFILE_r*.json")))
    assert artifacts, "no committed PROFILE_rNN.json round artifact"
    with open(artifacts[-1]) as f:
        obj = json.load(f)
    if "slots_sweep" in obj:
        assert set(obj) == SWEEP_KEYS
        for rung in obj["rungs"]:
            assert set(rung) == RUNG_KEYS
    else:
        assert set(obj) == REQUIRED_KEYS
