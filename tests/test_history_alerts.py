"""Retained-telemetry tests: MetricHistory ring semantics and windowed
aggregation, the shared /debug query parser's 400 contract, the
AlertEngine state machine (pending→firing→resolved, exactly-once
on_fire), the count/byte-capped IncidentStore, the incident bundle +
markdown report join, and the HISTORY_INTERVAL_S=0 inertness pin across
the whole stack (no sampler thread, no alert engine, no disk writes)."""

import asyncio
import json
import os
import threading

import pytest
from aiohttp.test_utils import TestClient, TestServer

from generativeaiexamples_tpu.obs import history as obs_history
from generativeaiexamples_tpu.obs import incidents as obs_incidents
from generativeaiexamples_tpu.obs.alerts import (AlertEngine, AlertRule,
                                                 default_rules)
from generativeaiexamples_tpu.obs.history import MetricHistory
from generativeaiexamples_tpu.obs.incidents import (IncidentStore,
                                                    ObservabilityStack,
                                                    build_bundle)
from generativeaiexamples_tpu.obs.metrics import Registry


def _history(registry, window_s=60.0, interval_s=0.01, **kw):
    return MetricHistory(registry=registry, window_s=window_s,
                         interval_s=interval_s, **kw)


# ------------------------------------------------------------ history ring


def test_history_aggregates_gauge_and_reset_aware_counter_delta():
    reg = Registry()
    g = reg.gauge("g_load", "")
    c = reg.counter("c_events", "")
    hist = _history(reg)
    for v in (1.0, 3.0, 2.0):
        g.set(v)
        c.inc(2.0)
        hist.sample_once()
    q = hist.query()
    assert q["enabled"] and q["samples"] == 3
    gl = q["series"]["g_load"]
    assert gl["kind"] == "gauge"
    assert (gl["last"], gl["min"], gl["max"]) == (2.0, 1.0, 3.0)
    assert gl["avg"] == pytest.approx(2.0)
    assert "delta" not in gl                   # gauges don't get deltas
    ce = q["series"]["c_events"]
    assert ce["kind"] == "counter"
    assert ce["delta"] == pytest.approx(4.0)   # forward movement only
    assert ce["rate_per_s"] >= 0.0
    # a process restart drops the cumulative value mid-window: the
    # reset-aware delta must not go negative or swallow later increments
    c._value = 0.0                             # simulate restart
    c.inc(1.0)
    hist.sample_once()                         # backwards step clamps to 0
    assert hist.query()["series"]["c_events"]["delta"] == pytest.approx(4.0)
    c.inc(3.0)
    hist.sample_once()                         # post-reset growth counts
    assert hist.query()["series"]["c_events"]["delta"] == pytest.approx(7.0)


def test_history_glob_filter_matches_base_name_and_labeled_keys():
    reg = Registry()
    reg.gauge("router_slo_attainment", "", labelnames=("replica",)) \
        .labels("r0").set(0.5)
    reg.gauge("other_gauge", "").set(1.0)
    hist = _history(reg)
    hist.sample_once()
    keys = set(hist.query(metrics="router_slo*")["series"])
    assert keys == {'router_slo_attainment{replica="r0"}'}
    assert set(hist.query()["series"]) >= {"other_gauge"}


def test_history_ring_bounded_by_window():
    reg = Registry()
    reg.gauge("g", "").set(1.0)
    hist = _history(reg, window_s=1.0, interval_s=0.25)
    cap = hist._ring.maxlen
    assert cap == int(1.0 / 0.25) + 1
    for _ in range(cap * 3):
        hist.sample_once()
    assert hist.samples == cap


def test_history_inert_when_interval_zero_no_thread_no_samples():
    hist = _history(Registry(), interval_s=0.0)
    hist.start()                               # must be a no-op
    assert not hist.enabled
    assert hist._thread is None                # no sampler thread spawned
    q = hist.query()
    assert q == {"enabled": False, "interval_s": 0.0, "window_s": 60.0,
                 "samples": 0, "span_s": 0.0, "series": {}}


def test_history_sampler_thread_ticks_and_stops():
    reg = Registry()
    reg.gauge("g", "").set(7.0)
    hist = _history(reg, interval_s=0.01)
    ticks = []
    hist.on_sample.append(lambda h: ticks.append(h.samples))
    hist.start()
    thread = hist._thread
    assert thread is not None and thread.name == "metric-history"
    deadline = 100
    while hist.samples < 3 and deadline:
        deadline -= 1
        threading.Event().wait(0.02)
    hist.stop()
    assert hist.samples >= 3 and ticks
    assert not thread.is_alive()               # stop() joined OUR thread


# ------------------------------------------------------- alert state machine


def _stall_rule(**kw):
    base = dict(window_s=30.0, for_s=0.0, severity="critical")
    base.update(kw)
    return AlertRule("stall", "engine_watchdog_stalls", "delta", ">",
                     0.0, **base)


def test_alert_fires_once_per_episode_and_resolves():
    reg = Registry()
    g = reg.gauge("engine_watchdog_stalls", "")
    g.set(0.0)
    hist = _history(reg)
    fired = []
    eng = AlertEngine(hist, rules=(_stall_rule(),), registry=reg,
                      on_fire=lambda r, rec: fired.append(rec)).attach()
    hist.sample_once()                         # flat baseline
    assert eng._states["stall"].state == "ok"
    g.set(1.0)                                 # the breach
    hist.sample_once()
    assert eng._states["stall"].state == "firing"
    assert len(fired) == 1
    assert fired[0]["evidence"]["series"]["engine_watchdog_stalls"][
        "value"] > 0
    hist.sample_once()                         # stays firing: no re-fire
    hist.sample_once()
    assert len(fired) == 1
    assert reg.snapshot()['alerts_firing{rule="stall"}'] == 1.0
    # flat again long enough that the delta leaves the window: use a
    # tiny window engine to avoid sleeping
    eng2_hist = _history(reg, window_s=0.01)
    import time
    eng2 = AlertEngine(eng2_hist, rules=(_stall_rule(window_s=0.05),),
                       registry=reg, on_fire=lambda r, rec: None)
    eng2_hist.sample_once()
    time.sleep(0.08)
    eng2_hist.sample_once()
    eng2.tick()
    assert eng2._states["stall"].state == "ok"


def test_alert_for_duration_debounce_pending_then_firing():
    reg = Registry()
    g = reg.gauge("engine_watchdog_stalls", "")
    g.set(0.0)
    hist = _history(reg)
    fired = []
    eng = AlertEngine(hist, rules=(_stall_rule(for_s=3600.0),),
                      registry=reg,
                      on_fire=lambda r, rec: fired.append(rec))
    hist.sample_once()
    g.set(1.0)
    hist.sample_once()
    eng.tick(now=1000.0)
    assert eng._states["stall"].state == "pending" and not fired
    eng.tick(now=1000.0 + 10.0)                # still inside for_s
    assert eng._states["stall"].state == "pending" and not fired
    eng.tick(now=1000.0 + 3601.0)              # debounce satisfied
    assert eng._states["stall"].state == "firing"
    assert len(fired) == 1
    assert eng._states["stall"].episodes == 1


def test_alert_refire_after_resolve_is_a_new_episode():
    reg = Registry()
    g = reg.gauge("engine_watchdog_stalls", "")
    g.set(0.0)
    hist = _history(reg, window_s=0.2)
    fired = []
    eng = AlertEngine(hist, rules=(_stall_rule(window_s=0.2),),
                      registry=reg,
                      on_fire=lambda r, rec: fired.append(rec))
    import time
    hist.sample_once()
    g.set(1.0)
    hist.sample_once()
    eng.tick()
    assert eng._states["stall"].state == "firing"
    time.sleep(0.25)                           # breach ages out
    hist.sample_once()
    eng.tick()
    assert eng._states["stall"].state == "ok"
    g.set(2.0)                                 # second stall
    hist.sample_once()
    eng.tick()
    assert eng._states["stall"].state == "firing"
    assert len(fired) == 2
    assert eng._states["stall"].episodes == 2
    snap = reg.snapshot()
    assert snap['alerts_total{rule="stall",state="firing"}'] == 2.0
    assert snap['alerts_total{rule="stall",state="resolved"}'] == 1.0


def test_alert_snapshot_shape_and_firing_headline():
    reg = Registry()
    g = reg.gauge("engine_watchdog_stalls", "")
    g.set(0.0)
    hist = _history(reg)
    eng = AlertEngine(hist, rules=(_stall_rule(),), registry=reg)
    hist.sample_once()
    g.set(1.0)
    hist.sample_once()
    eng.tick()
    snap = eng.snapshot()
    assert snap["enabled"] and snap["firing"] == ["stall"]
    row = next(r for r in snap["rules"] if r["rule"] == "stall")
    assert row["state"] == "firing" and row["severity"] == "critical"
    assert row["evidence"]["series"]


def test_default_rules_per_tier_and_env_thresholds(monkeypatch):
    monkeypatch.setenv("ALERT_DRIFT_RATIO_MAX", "9.5")
    chain = {r.name: r for r in default_rules("chain")}
    router = {r.name: r for r in default_rules("router")}
    assert {"engine_watchdog_stall", "kv_restore_corrupt",
            "sched_cost_drift", "breaker_flap",
            "shed_rate"} == set(chain)
    assert {"slo_burn_rate", "heartbeat_stale", "breaker_flap",
            "shed_rate"} == set(router)
    assert chain["sched_cost_drift"].threshold == 9.5
    with pytest.raises(ValueError):
        AlertRule("bad", "m", "median", ">", 0.0)
    with pytest.raises(ValueError):
        AlertRule("bad", "m", "avg", "~", 0.0)


# --------------------------------------------------------- incident store


def _bundle(i, pad=0):
    return {"schema": "incident/v1", "server": "chain",
            "ts": 1000.0 + i,
            "trigger": {"kind": "alert", "rule": "stall",
                        "evidence": {"series": {"m": {"value": 1.0}}}},
            "alerts": None,
            "history": {"aggregates": {"series": {}}, "window": []},
            "flight": None, "rounds": None, "pad": "x" * pad}


def test_incident_store_capture_list_load_roundtrip(tmp_path):
    store = IncidentStore(root=str(tmp_path / "inc"), max_count=10,
                          max_bytes=1 << 20)
    path = store.capture(_bundle(0))
    assert path and os.path.exists(path)
    listing = store.list()
    assert listing["count"] == 1
    entry = listing["incidents"][0]
    assert entry["rule"] == "stall" and entry["kind"] == "alert"
    loaded = store.load(entry["id"])
    assert loaded["schema"] == "incident/v1"
    assert store.load("no-such-incident") is None


def test_incident_store_count_cap_evicts_oldest(tmp_path):
    store = IncidentStore(root=str(tmp_path / "inc"), max_count=3,
                          max_bytes=1 << 20)
    paths = [store.capture(_bundle(i)) for i in range(5)]
    names = sorted(os.listdir(store.root))
    assert len(names) == 3
    # the two oldest were evicted
    assert os.path.basename(paths[0]) not in names
    assert os.path.basename(paths[1]) not in names
    assert store.list()["count"] == 3


def test_incident_store_byte_cap_evicts_oldest(tmp_path):
    store = IncidentStore(root=str(tmp_path / "inc"), max_count=100,
                          max_bytes=6000)
    for i in range(4):
        store.capture(_bundle(i, pad=2000))    # each bundle > 2 KB
    listing = store.list()
    assert listing["total_bytes"] <= 6000
    assert listing["count"] < 4


def test_incident_store_path_traversal_guarded(tmp_path):
    secret = tmp_path / "secret.json"
    secret.write_text("{}")
    store = IncidentStore(root=str(tmp_path / "inc"))
    store.capture(_bundle(0))
    assert store.load("../secret") is None


def test_build_bundle_joins_history_flight_rounds_and_extras():
    from generativeaiexamples_tpu.obs.flight import FlightRecorder

    reg = Registry()
    reg.gauge("g", "").set(1.0)
    hist = _history(reg)
    hist.sample_once()
    flight = FlightRecorder(completed_cap=8)
    flight.complete(flight.begin("req-1"))
    bundle = build_bundle(server="router",
                          trigger={"kind": "manual", "rule": None},
                          history=hist, alerts=None, flight=flight,
                          rounds=None, extras={"fleet": {"replicas": 2}})
    assert bundle["schema"] == "incident/v1"
    assert bundle["server"] == "router"
    assert bundle["history"]["window"]
    assert bundle["history"]["aggregates"]["series"]["g"]["last"] == 1.0
    assert [t["request_id"] for t in bundle["flight"]["completed"]] \
        == ["req-1"]
    assert bundle["fleet"] == {"replicas": 2}
    assert json.dumps(bundle)                  # JSON-serializable


def test_incident_report_renders_markdown_with_request_join(tmp_path):
    from tools.incident_report import render_markdown

    from generativeaiexamples_tpu.obs.flight import FlightRecorder

    reg = Registry()
    reg.gauge("engine_watchdog_stalls", "").set(1.0)
    hist = _history(reg)
    hist.sample_once()
    flight = FlightRecorder(completed_cap=8)
    flight.complete(flight.begin("joined-req-9"))
    bundle = build_bundle(
        server="chain",
        trigger={"kind": "alert", "rule": "engine_watchdog_stall",
                 "severity": "critical", "summary": "stalled",
                 "evidence": {"series": {"engine_watchdog_stalls":
                                         {"value": 1.0}}}},
        history=hist, alerts=None, flight=flight, rounds=None)
    bundle["id"] = "inc-test-1"
    report = render_markdown(bundle)
    assert "engine_watchdog_stall" in report
    assert "joined-req-9" in report
    assert "inc-test-1" in report


# ------------------------------------------------- stack inertness + HTTP


def test_stack_inert_when_interval_zero_no_alerts_no_store(tmp_path,
                                                           monkeypatch):
    monkeypatch.setenv("GAIE_RUN_DIR", str(tmp_path / "run"))
    stack = ObservabilityStack("chain", registry=Registry(),
                               interval_s=0.0)
    stack.start()
    assert not stack.enabled
    assert stack.alerts is None and stack.store is None
    assert stack.capture({"kind": "manual"}) is None
    assert not (tmp_path / "run").exists()     # zero disk writes
    assert stack.history._thread is None


def test_stack_armed_capture_writes_bundle_with_extras(tmp_path,
                                                       monkeypatch):
    monkeypatch.setenv("GAIE_RUN_DIR", str(tmp_path / "run"))
    stack = ObservabilityStack(
        "chain", registry=Registry(), interval_s=0.01,
        capture_extras=lambda: {"fleet": {"n": 1}})
    stack.history.sample_once()
    path = stack.capture({"kind": "manual", "rule": None})
    assert path and path.startswith(str(tmp_path / "run"))
    with open(path, encoding="utf-8") as fh:
        bundle = json.load(fh)
    assert bundle["fleet"] == {"n": 1}
    assert bundle["alerts"]["enabled"]         # alert engine attached


def _run(coro):
    return asyncio.get_event_loop_policy().new_event_loop() \
        .run_until_complete(coro)


from generativeaiexamples_tpu.chains.base import BaseExample


class _EchoExample(BaseExample):
    """Minimal example for endpoint tests."""

    def llm_chain(self, context, question, num_tokens):
        yield "ok"

    def rag_chain(self, prompt, num_tokens):
        yield "ok"

    def ingest_docs(self, data_dir, filename):
        pass


def test_chain_server_debug_endpoints_armed(tmp_path, monkeypatch):
    from generativeaiexamples_tpu.chains.server import create_app

    monkeypatch.setenv("GAIE_RUN_DIR", str(tmp_path / "run"))
    monkeypatch.setattr(obs_history, "HISTORY_INTERVAL_S", 0.02)

    async def fn():
        client = TestClient(TestServer(create_app(_EchoExample())))
        await client.start_server()
        try:
            # the sampler thread populates history shortly after startup
            for _ in range(100):
                hist = await (await client.get("/debug/history")).json()
                if hist["enabled"] and hist["samples"] >= 2:
                    break
                await asyncio.sleep(0.02)
            assert hist["samples"] >= 2 and hist["series"]
            # glob filtering via the query param
            filtered = await (await client.get(
                "/debug/history?metrics=engine_*")).json()
            assert all(k.startswith("engine_")
                       for k in filtered["series"])

            alerts = await (await client.get("/debug/alerts")).json()
            assert alerts["enabled"] and alerts["server"] == "chain"
            assert {r["rule"] for r in alerts["rules"]} \
                == {r.name for r in default_rules("chain")}
            assert alerts["ticks"] >= 1        # attached to the sampler

            # uniform query validation: 400 JSON body + X-Request-ID
            resp = await client.get("/debug/history?window=bogus",
                                    headers={"X-Request-ID": "q-1"})
            assert resp.status == 400
            assert resp.headers["X-Request-ID"] == "q-1"
            body = await resp.json()
            assert body["error"]["type"] == "bad_query"
            assert body["request_id"] == "q-1"
            assert (await client.get(
                "/debug/incidents?limit=-2")).status == 400

            # manual black-box capture -> listed -> loadable by id
            resp = await client.post("/control/incident",
                                     json={"reason": "drill"})
            assert resp.status == 200
            captured = await resp.json()
            assert captured["status"] == "captured"
            listing = await (await client.get("/debug/incidents")).json()
            assert listing["enabled"] and listing["count"] == 1
            assert listing["incidents"][0]["id"] == captured["id"]
            bundle = await (await client.get(
                f"/debug/incidents?id={captured['id']}")).json()
            assert bundle["schema"] == "incident/v1"
            assert bundle["trigger"]["kind"] == "manual"
            assert bundle["trigger"]["reason"] == "drill"
            assert (await client.get(
                "/debug/incidents?id=nope")).status == 404
        finally:
            await client.close()

    _run(fn())


def test_chain_server_debug_endpoints_inert(tmp_path, monkeypatch):
    from generativeaiexamples_tpu.chains.server import create_app

    monkeypatch.setenv("GAIE_RUN_DIR", str(tmp_path / "run"))
    monkeypatch.setattr(obs_history, "HISTORY_INTERVAL_S", 0.0)

    async def fn():
        before = {t.name for t in threading.enumerate()}
        client = TestClient(TestServer(create_app(_EchoExample())))
        await client.start_server()
        try:
            assert "metric-history" not in \
                {t.name for t in threading.enumerate()} - before
            hist = await (await client.get("/debug/history")).json()
            assert hist == {**hist, "enabled": False, "samples": 0}
            alerts = await (await client.get("/debug/alerts")).json()
            assert alerts["enabled"] is False and alerts["firing"] == []
            listing = await (await client.get("/debug/incidents")).json()
            assert listing == {"enabled": False, "count": 0,
                               "incidents": []}
            resp = await client.post("/control/incident", json={})
            assert resp.status == 409
            body = await resp.json()
            assert body["error"]["type"] == "incidents_disabled"
            assert not (tmp_path / "run").exists()
        finally:
            await client.close()

    _run(fn())
