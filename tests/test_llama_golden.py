"""Golden-parity tests: JAX Llama vs transformers on CPU.

The reference has no engine-correctness tests at all (SURVEY.md §4); its
parity story is manual smoke tests. Here every model change is gated on
logit parity with the HF implementation.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import LLAMA_TINY, LlamaConfig
from generativeaiexamples_tpu.models.import_hf import params_from_hf_model

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


@pytest.fixture(scope="module")
def hf_model_and_params():
    hf_cfg = transformers.LlamaConfig(
        vocab_size=LLAMA_TINY.vocab_size,
        hidden_size=LLAMA_TINY.hidden_size,
        intermediate_size=LLAMA_TINY.intermediate_size,
        num_hidden_layers=LLAMA_TINY.num_layers,
        num_attention_heads=LLAMA_TINY.num_heads,
        num_key_value_heads=LLAMA_TINY.num_kv_heads,
        max_position_embeddings=LLAMA_TINY.max_position_embeddings,
        rms_norm_eps=LLAMA_TINY.rms_norm_eps,
        rope_theta=LLAMA_TINY.rope_theta,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    params = params_from_hf_model(model, LLAMA_TINY, dtype=jnp.float32)
    return model, params


def hf_logits(model, tokens: np.ndarray) -> np.ndarray:
    with torch.no_grad():
        out = model(torch.from_numpy(tokens).long())
    return out.logits.float().numpy()


def test_forward_matches_hf(hf_model_and_params):
    model, params = hf_model_and_params
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, LLAMA_TINY.vocab_size, size=(2, 17), dtype=np.int32)
    positions = np.broadcast_to(np.arange(17, dtype=np.int32), (2, 17))

    ours, _ = llama.apply(params, LLAMA_TINY, jnp.asarray(tokens),
                          jnp.asarray(positions))
    theirs = hf_logits(model, tokens)
    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=2e-4, atol=2e-4)


def test_gqa_grouping_is_nontrivial():
    # LLAMA_TINY must actually exercise GQA (H != KV) for the golden test
    # to cover the grouped path.
    assert LLAMA_TINY.num_heads != LLAMA_TINY.num_kv_heads


def test_kv_cache_decode_matches_full_forward(hf_model_and_params):
    """Prefill+decode through the cache must equal the full forward."""
    _, params = hf_model_and_params
    cfg = LLAMA_TINY
    rng = np.random.default_rng(1)
    B, S_total, S_prefill = 2, 12, 8
    tokens = rng.integers(0, cfg.vocab_size, size=(B, S_total), dtype=np.int32)
    all_pos = np.broadcast_to(np.arange(S_total, dtype=np.int32), (B, S_total))

    full_logits, _ = llama.apply(params, cfg, jnp.asarray(tokens),
                                 jnp.asarray(all_pos))

    cache = llama.init_kv_cache(cfg, B, max_len=32, dtype=jnp.float32)
    pre_logits, cache = llama.apply(
        params, cfg, jnp.asarray(tokens[:, :S_prefill]),
        jnp.asarray(all_pos[:, :S_prefill]), cache)
    np.testing.assert_allclose(np.asarray(pre_logits),
                               np.asarray(full_logits[:, :S_prefill]),
                               rtol=1e-4, atol=1e-4)

    for t in range(S_prefill, S_total):
        step_logits, cache = llama.apply(
            params, cfg, jnp.asarray(tokens[:, t:t + 1]),
            jnp.asarray(all_pos[:, t:t + 1]), cache)
        np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                                   np.asarray(full_logits[:, t]),
                                   rtol=1e-4, atol=1e-4)


def test_ragged_batch_padding_invariance(hf_model_and_params):
    """A short row padded inside a longer batch must produce the same
    logits as the same row alone (mask correctness)."""
    _, params = hf_model_and_params
    cfg = LLAMA_TINY
    rng = np.random.default_rng(2)
    short = rng.integers(0, cfg.vocab_size, size=(1, 5), dtype=np.int32)
    long_ = rng.integers(0, cfg.vocab_size, size=(1, 9), dtype=np.int32)

    pos5 = np.arange(5, dtype=np.int32)[None]
    alone, _ = llama.apply(params, cfg, jnp.asarray(short), jnp.asarray(pos5),
                           kv_valid_len=jnp.asarray([5]))

    batch = np.zeros((2, 9), dtype=np.int32)
    batch[0, :5] = short[0]
    batch[1] = long_[0]
    pos9 = np.broadcast_to(np.arange(9, dtype=np.int32), (2, 9))
    batched, _ = llama.apply(params, cfg, jnp.asarray(batch),
                             jnp.asarray(pos9),
                             kv_valid_len=jnp.asarray([5, 9]))
    np.testing.assert_allclose(np.asarray(batched[0, :5]),
                               np.asarray(alone[0]), rtol=1e-4, atol=1e-4)


def test_jit_compiles_once_for_decode(hf_model_and_params):
    _, params = hf_model_and_params
    cfg = LLAMA_TINY
    cache = llama.init_kv_cache(cfg, 2, max_len=32, dtype=jnp.float32)

    calls = {"n": 0}

    @jax.jit
    def step(params, tokens, positions, cache):
        calls["n"] += 1
        return llama.apply(params, cfg, tokens, positions, cache)

    toks = jnp.zeros((2, 1), jnp.int32)
    for t in range(3):
        pos = jnp.full((2, 1), t, jnp.int32)
        _, cache = step(params, toks, pos, cache)
    assert calls["n"] == 1  # traced exactly once


def test_moe_forward_runs():
    """Mixtral-geometry MoE forward produces finite logits (EP parity comes
    in parallel/)."""
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                      num_experts=4, num_experts_per_tok=2)
    params = llama.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    tokens = jnp.zeros((1, 7), jnp.int32)
    pos = jnp.arange(7, dtype=jnp.int32)[None]
    logits, _ = llama.apply(params, cfg, tokens, pos)
    assert logits.shape == (1, 7, 128)
    assert bool(jnp.isfinite(logits).all())


def _meta_state_dict(hf_model, cfg):
    """Render HF weights under Meta/fairscale names + interleaved RoPE."""
    import torch

    sd = hf_model.state_dict()

    def permute_to_meta(w, n_heads):
        # inverse of transformers' convert_llama_weights_to_hf permutation
        out_dim, in_dim = w.shape
        return (w.reshape(n_heads, 2, cfg.head_dim // 2, in_dim)
                 .transpose(0, 2, 1, 3).reshape(out_dim, in_dim))

    meta = {}
    for key, t in sd.items():
        arr = t.detach().to(torch.float32).numpy()
        key = key.removeprefix("model.")
        if key == "embed_tokens.weight":
            meta["tok_embeddings.weight"] = arr
        elif key == "norm.weight":
            meta["norm.weight"] = arr
        elif key == "lm_head.weight":
            meta["output.weight"] = arr
        else:
            m = key.split(".")
            li, rest = m[1], ".".join(m[2:])
            name_map = {
                "input_layernorm.weight": "attention_norm.weight",
                "post_attention_layernorm.weight": "ffn_norm.weight",
                "self_attn.q_proj.weight": "attention.wq.weight",
                "self_attn.k_proj.weight": "attention.wk.weight",
                "self_attn.v_proj.weight": "attention.wv.weight",
                "self_attn.o_proj.weight": "attention.wo.weight",
                "mlp.gate_proj.weight": "feed_forward.w1.weight",
                "mlp.up_proj.weight": "feed_forward.w3.weight",
                "mlp.down_proj.weight": "feed_forward.w2.weight",
            }
            if rest == "self_attn.q_proj.weight":
                arr = permute_to_meta(arr, cfg.num_heads)
            elif rest == "self_attn.k_proj.weight":
                arr = permute_to_meta(arr, cfg.num_kv_heads)
            meta[f"layers.{li}.{name_map[rest]}"] = arr
    return meta


def _assert_trees_close(got, params):
    import numpy as np

    def cmp(a, b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    jax.tree.map(cmp, got, params)


def test_meta_pth_import_matches_hf(hf_model_and_params):
    """A Meta-format (fairscale-named, interleaved-RoPE) rendering of the same
    weights must import to the identical param tree as the HF naming."""
    from generativeaiexamples_tpu.models import import_hf

    hf_model, params = hf_model_and_params
    meta = _meta_state_dict(hf_model, LLAMA_TINY)
    got = import_hf.params_from_named_tensors(
        iter(meta.items()), LLAMA_TINY, dtype=jnp.float32)
    _assert_trees_close(got, params)


def test_meta_multishard_import_matches_hf(hf_model_and_params, tmp_path):
    """Two fairscale TP shards (consolidated.00/01.pth) must merge back to
    the single logical tree (regression: shards used to silently overwrite
    each other, ADVICE.md r1 medium)."""
    import torch

    from generativeaiexamples_tpu.models import import_hf

    hf_model, params = hf_model_and_params
    meta = _meta_state_dict(hf_model, LLAMA_TINY)

    shard_dims = import_hf._META_SHARD_DIM
    shards = [{}, {}]
    for key, arr in meta.items():
        dim = import_hf._meta_shard_dim(key)
        t = torch.from_numpy(arr)
        if dim is None:
            shards[0][key] = t.clone()
            shards[1][key] = t.clone()
        else:
            a, b = torch.chunk(t, 2, dim=dim)
            shards[0][key], shards[1][key] = a.contiguous(), b.contiguous()
    assert shard_dims  # the table itself must exist
    torch.save(shards[0], tmp_path / "consolidated.00.pth")
    torch.save(shards[1], tmp_path / "consolidated.01.pth")

    got = import_hf.load_checkpoint(str(tmp_path), LLAMA_TINY,
                                    dtype=jnp.float32)
    _assert_trees_close(got, params)
