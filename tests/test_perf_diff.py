"""tools/perf_diff.py: the perf regression gate — headline-metric
extraction, threshold semantics, CLI exit codes, and a tier-1 run over
the committed BENCH_rNN artifacts."""

import json
import os

import pytest

from tools.perf_diff import (DEFAULT_THRESHOLD_PCT, compare,
                             extract_metrics, main)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _result(**over):
    base = {
        "decode_tokens_per_sec": 500.0,
        "engine_p50_ttft_ms": 150.0,
        "engine_p99_ttft_ms": 180.0,
        "hbm_bw_util": 0.72,
        "chat": {"warm_p50_ttft_ms": 40.0,
                 "spec": {"tokens_per_step": 1.8}},
        "openloop": {"rates": [
            {"arrival_rps": 2.0, "slo_attainment": 0.95,
             "goodput_tokens_per_sec": 900.0},
            {"arrival_rps": 4.0, "slo_attainment": 0.80,
             "goodput_tokens_per_sec": 1500.0},
        ]},
    }
    base.update(over)
    return base


def test_extract_flattens_headline_metrics():
    m = extract_metrics(_result())
    assert m["decode_tokens_per_sec"] == (500.0, "higher")
    assert m["engine_p50_ttft_ms"] == (150.0, "lower")
    assert m["chat.warm_p50_ttft_ms"] == (40.0, "lower")
    assert m["slo_attainment@2"] == (0.95, "higher")
    assert m["goodput_tokens_per_sec@4"] == (1500.0, "higher")
    assert m["spec.tokens_per_step"] == (1.8, "higher")
    # driver artifact wrapper unwraps
    assert extract_metrics({"parsed": _result()})["hbm_bw_util"][0] == 0.72


def test_extract_fleet_policy_metrics_direction_aware():
    """Fleet arms contribute per-policy headline metrics (ISSUE 12): a
    cross-replica prefix-hit or SLO regression in one arm is gated like
    any single-replica headline, and a warm-TTFT rise is wrong-way."""
    result = _result(fleet={"policies": [
        {"policy": "round_robin", "prefix_hit_rate": 0.05,
         "slo_attainment": 0.90, "ttft_p50_ms": 120.0,
         "kv_transfer_pages": 0},
        {"policy": "affinity_transfer", "prefix_hit_rate": 0.62,
         "slo_attainment": 0.99, "ttft_p50_ms": 45.0,
         "kv_transfer_pages": 12},
    ]})
    m = extract_metrics(result)
    assert m["fleet.prefix_hit_rate@affinity_transfer"] == (0.62, "higher")
    assert m["fleet.slo_attainment@round_robin"] == (0.90, "higher")
    assert m["fleet.ttft_p50_ms@affinity_transfer"] == (45.0, "lower")
    assert m["fleet.kv_transfer_pages@affinity_transfer"] == (12, "higher")
    # direction-aware comparison: a prefix-hit drop regresses, a TTFT
    # drop improves
    worse = extract_metrics(_result(fleet={"policies": [
        {"policy": "affinity_transfer", "prefix_hit_rate": 0.30,
         "slo_attainment": 0.99, "ttft_p50_ms": 30.0,
         "kv_transfer_pages": 12},
    ]}))
    regressions, notes = compare(m, worse)
    assert any("fleet.prefix_hit_rate@affinity_transfer" in r
               for r in regressions)
    assert any(n.startswith("improved fleet.ttft_p50_ms")
               for n in notes)


def test_extract_autoscale_policy_metrics_direction_aware():
    """Autoscale arms contribute per-policy headline gates (ISSUE 13):
    attainment is gated UP and replica_minutes DOWN — an attainment
    'win' bought by quietly spending a fatter fleet is a regression on
    the bill, and the gate must say so."""
    result = _result(autoscale={"policies": [
        {"policy": "autoscaled", "slo_attainment": 0.97,
         "replica_minutes": 0.42, "ttft_p50_ms": 90.0},
        {"policy": "static", "slo_attainment": 0.81,
         "replica_minutes": 0.42, "ttft_p50_ms": 150.0},
    ]})
    m = extract_metrics(result)
    assert m["autoscale.slo_attainment@autoscaled"] == (0.97, "higher")
    assert m["autoscale.replica_minutes@autoscaled"] == (0.42, "lower")
    assert m["autoscale.slo_attainment@static"] == (0.81, "higher")
    worse = extract_metrics(_result(autoscale={"policies": [
        {"policy": "autoscaled", "slo_attainment": 0.80,
         "replica_minutes": 0.80, "ttft_p50_ms": 90.0},
    ]}))
    regressions, _ = compare(m, worse)
    assert any("autoscale.slo_attainment@autoscaled" in r
               for r in regressions)
    # MORE replica-minutes is the wrong direction
    assert any("autoscale.replica_minutes@autoscaled" in r
               for r in regressions)


def test_extract_multichip_rung_metrics_direction_aware():
    """Multichip rungs contribute per-mesh gates (ISSUE 14): tokens/s
    is gated UP and TTFT DOWN per rung, so a tp=2 rung that quietly
    slowed to single-chip speed regresses the gate even when the tp=1
    rung held."""
    result = _result(multichip={"rungs": [
        {"mesh": "tp=1", "decode_tokens_per_sec": 500.0,
         "engine_p50_ttft_ms": 150.0},
        {"mesh": "tp=2", "decode_tokens_per_sec": 900.0,
         "engine_p50_ttft_ms": 95.0},
    ]})
    m = extract_metrics(result)
    assert m["multichip.tokens_per_sec@tp=2"] == (900.0, "higher")
    assert m["multichip.ttft_p50_ms@tp=2"] == (95.0, "lower")
    assert m["multichip.tokens_per_sec@tp=1"] == (500.0, "higher")
    worse = extract_metrics(_result(multichip={"rungs": [
        {"mesh": "tp=2", "decode_tokens_per_sec": 500.0,
         "engine_p50_ttft_ms": 150.0},
    ]}))
    regressions, _ = compare(m, worse)
    assert any("multichip.tokens_per_sec@tp=2" in r for r in regressions)
    assert any("multichip.ttft_p50_ms@tp=2" in r for r in regressions)


def test_extract_disagg_arm_metrics_direction_aware():
    """Disagg arms contribute per-arm gates (docs/disaggregation.md):
    the scenario's claim is the disagg arm wins BOTH p50 TTFT (down)
    and decode goodput (up), so each is gated round-over-round — a
    handoff path that quietly stopped protecting decode rounds
    regresses the gate even when the unified arm held."""
    result = _result(disagg={"arms": [
        {"arm": "unified", "ttft_p50_ms": 120.0,
         "decode_goodput": 60.0},
        {"arm": "disagg", "ttft_p50_ms": 80.0,
         "decode_goodput": 90.0},
    ]})
    m = extract_metrics(result)
    assert m["disagg.ttft_p50_ms@disagg"] == (80.0, "lower")
    assert m["disagg.decode_goodput@disagg"] == (90.0, "higher")
    assert m["disagg.ttft_p50_ms@unified"] == (120.0, "lower")
    assert m["disagg.decode_goodput@unified"] == (60.0, "higher")
    # the disagg arm regressing toward unified trips BOTH gates
    worse = extract_metrics(_result(disagg={"arms": [
        {"arm": "disagg", "ttft_p50_ms": 115.0,
         "decode_goodput": 62.0},
    ]}))
    regressions, _ = compare(m, worse)
    assert any("disagg.ttft_p50_ms@disagg" in r for r in regressions)
    assert any("disagg.decode_goodput@disagg" in r for r in regressions)


def test_extract_tolerates_missing_sections():
    m = extract_metrics({"decode_tokens_per_sec": 100.0, "chat": {}})
    assert set(m) == {"decode_tokens_per_sec"}


def test_compare_direction_aware():
    base = extract_metrics(_result())
    # throughput DOWN 20% -> regression; TTFT DOWN 20% -> improvement
    new = extract_metrics(_result(decode_tokens_per_sec=400.0,
                                  engine_p50_ttft_ms=120.0))
    regressions, notes = compare(base, new)
    assert any("decode_tokens_per_sec" in r for r in regressions)
    assert not any("engine_p50_ttft_ms" in r for r in regressions)
    assert any(n.startswith("improved engine_p50_ttft_ms")
               for n in notes)
    # inside the default threshold: no regression
    small = extract_metrics(_result(
        decode_tokens_per_sec=500.0 * (1 - DEFAULT_THRESHOLD_PCT / 200)))
    assert compare(base, small)[0] == []


def test_compare_per_metric_threshold_and_skips():
    base = extract_metrics(_result())
    new = extract_metrics(_result(decode_tokens_per_sec=460.0))  # -8%
    assert compare(base, new)[0]                       # default 5% trips
    regs, _ = compare(base, new,
                      per_metric_pct={"decode_tokens_per_sec": 10.0})
    assert regs == []                                  # widened: passes
    # a metric absent from one side is skipped with a note, not a fail
    lean = extract_metrics({"decode_tokens_per_sec": 500.0})
    regs, notes = compare(base, lean)
    assert regs == []
    assert any(n.startswith("skip engine_p50_ttft_ms") for n in notes)


def test_cli_exit_codes(tmp_path, capsys):
    base_p = tmp_path / "base.json"
    new_p = tmp_path / "new.json"
    base_p.write_text(json.dumps(_result()))
    new_p.write_text(json.dumps(_result(decode_tokens_per_sec=300.0)))
    assert main([str(base_p), str(base_p)]) == 0
    assert main([str(base_p), str(new_p)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "decode_tokens_per_sec" in out
    # per-metric override rescues it
    assert main([str(base_p), str(new_p),
                 "--threshold", "decode_tokens_per_sec=50"]) == 0
    # unusable artifacts are a usage error, not a crash
    empty = tmp_path / "empty.json"
    empty.write_text("{}")
    assert main([str(base_p), str(empty)]) == 2
    assert main([str(base_p), str(tmp_path / "missing.json")]) == 2


@pytest.mark.parametrize("pair,expect", [
    (("BENCH_r04.json", "BENCH_r05.json"), 0),   # r05 did not regress r04
    (("BENCH_r01.json", "BENCH_r05.json"), 0),   # the whole trajectory
])
def test_committed_artifacts_gate(pair, expect):
    """Tier-1 over the committed round artifacts: the recorded perf
    trajectory is monotone enough that each later round passes the gate
    against the earlier one (p99 wobble gets a wider threshold — single
    -digit-sample tail percentiles jitter between runs)."""
    base, new = (os.path.join(REPO, p) for p in pair)
    rc = main([base, new, "--threshold", "engine_p99_ttft_ms=20"])
    assert rc == expect
