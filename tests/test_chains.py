"""Chain-layer tests: splitter, readers, LLM clients, the developer_rag
example, and the 3-endpoint HTTP server (run with aiohttp test utils and a
fake LLM/embedder — the layer-test the reference never had, SURVEY.md §4)."""

import asyncio
import json
import os
import zlib

import pytest

import aiohttp
from aiohttp.test_utils import TestClient, TestServer

from generativeaiexamples_tpu.chains.base import BaseExample
from generativeaiexamples_tpu.chains.examples.developer_rag import QAChatbot
from generativeaiexamples_tpu.chains.llm import EchoLLM, OpenAICompatLLM, get_llm
from generativeaiexamples_tpu.chains.readers import read_document, read_pdf
from generativeaiexamples_tpu.chains.server import create_app, discover_example
from generativeaiexamples_tpu.chains.splitter import TokenTextSplitter, cap_context
from generativeaiexamples_tpu.embed.encoder import HashEmbedder
from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer
from generativeaiexamples_tpu.retrieval.docstore import DocumentIndex
from generativeaiexamples_tpu.utils.app_config import AppConfig
from generativeaiexamples_tpu.utils.configuration import from_dict
from generativeaiexamples_tpu.utils.errors import ChainError, ConfigError

TOK = ByteTokenizer()


# --------------------------------------------------------------- splitter

def test_splitter_respects_chunk_size():
    text = ". ".join(f"Sentence number {i} about TPUs" for i in range(100))
    sp = TokenTextSplitter(TOK, chunk_size=120, chunk_overlap=30)
    chunks = sp.split_text(text)
    assert len(chunks) > 3
    for c in chunks:
        assert len(TOK.encode(c, add_bos=False)) <= 120


def test_splitter_overlap_continuity():
    text = ". ".join(f"Alpha beta {i}" for i in range(60))
    sp = TokenTextSplitter(TOK, chunk_size=100, chunk_overlap=40)
    chunks = sp.split_text(text)
    # consecutive chunks share their boundary sentence(s)
    for a, b in zip(chunks, chunks[1:]):
        tail_sentence = a.split(". ")[-1].strip(". ")
        assert tail_sentence in b


def test_splitter_short_text_single_chunk():
    sp = TokenTextSplitter(TOK, chunk_size=510, chunk_overlap=200)
    assert sp.split_text("short text") == ["short text"]
    assert sp.split_text("   ") == []


def test_splitter_oversized_sentence_hard_split():
    sp = TokenTextSplitter(TOK, chunk_size=50, chunk_overlap=10)
    chunks = sp.split_text("x" * 400)  # one 'sentence' of 400 tokens
    assert len(chunks) >= 8
    assert "".join(chunks).count("x") == 400


def test_cap_context_budget():
    texts = ["a" * 100, "b" * 100, "c" * 100]  # 100 byte-tokens each
    kept = cap_context(texts, max_tokens=250, tokenizer=TOK)
    assert kept == texts[:2]


# ---------------------------------------------------------------- readers

def test_read_text_and_html(tmp_path):
    p = tmp_path / "doc.txt"
    p.write_text("hello world")
    assert read_document(str(p)) == "hello world"
    h = tmp_path / "doc.html"
    h.write_text("<html><body><script>x()</script><p>Visible text</p></body></html>")
    assert "Visible text" in read_document(str(h))
    assert "x()" not in read_document(str(h))


def _make_minimal_pdf(path: str, text: str) -> None:
    stream = f"BT /F1 12 Tf 72 720 Td ({text}) Tj ET".encode()
    compressed = zlib.compress(stream)
    body = (b"%PDF-1.4\n1 0 obj<</Length " + str(len(compressed)).encode()
            + b"/Filter/FlateDecode>>stream\n" + compressed
            + b"\nendstream endobj\ntrailer<<>>\n%%EOF")
    with open(path, "wb") as f:
        f.write(body)


def test_read_pdf_minimal(tmp_path):
    p = tmp_path / "doc.pdf"
    _make_minimal_pdf(str(p), "TPU systolic arrays rock")
    assert "TPU systolic arrays rock" in read_pdf(str(p))


def test_read_unsupported(tmp_path):
    p = tmp_path / "doc.xyz"
    p.write_text("x")
    with pytest.raises(ChainError):
        read_document(str(p))


# -------------------------------------------------------------------- llm

def test_echo_llm_streams_and_stops():
    llm = EchoLLM(prefix="", tail_chars=50)
    assert llm.complete("hello world", max_tokens=64) == "hello world"
    out = "".join(llm.stream("abc STOP def", max_tokens=64, stop=["STOP"]))
    assert "def" not in out


def test_get_llm_factory():
    cfg = from_dict(AppConfig, {"llm": {"model_engine": "echo"}})
    assert isinstance(get_llm(cfg), EchoLLM)
    cfg2 = from_dict(AppConfig, {"llm": {"model_engine": "openai-compat",
                                         "server_url": "http://x:1"}})
    assert isinstance(get_llm(cfg2), OpenAICompatLLM)
    with pytest.raises(ConfigError):
        get_llm(from_dict(AppConfig, {"llm": {"model_engine": "tpu-jax"}}))
    with pytest.raises(ConfigError):
        get_llm(from_dict(AppConfig, {"llm": {"model_engine": "nope"}}))


# ---------------------------------------------------------------- example

def _make_example() -> QAChatbot:
    cfg = from_dict(AppConfig, {
        "llm": {"model_engine": "echo"},
        "embeddings": {"model_engine": "hash", "dimensions": 64},
        "text_splitter": {"chunk_size": 100, "chunk_overlap": 20},
    })
    llm = EchoLLM(prefix="", tail_chars=4000)
    emb = HashEmbedder(dim=64)
    return QAChatbot(llm=llm, embedder=emb, config=cfg)


def test_developer_rag_ingest_and_chains(tmp_path):
    ex = _make_example()
    doc = tmp_path / "kb.txt"
    doc.write_text("The MXU is a 128x128 systolic array. "
                   "TPUs communicate over ICI links. "
                   "Paris is the capital of France.")
    ex.ingest_docs(str(doc), "kb.txt")
    assert len(ex.index) >= 1

    # rag_chain retrieves context and the prompt contains it
    out = "".join(ex.rag_chain("What is the MXU?", 4000))
    assert "systolic" in out  # retrieved context flowed into the prompt
    # llm_chain ignores the KB
    out2 = "".join(ex.llm_chain("", "What is the MXU?", 4000))
    assert "What is the MXU?" in out2

    hits = ex.document_search("systolic array", 2)
    assert hits and hits[0]["source"] == "kb.txt"
    assert {"score", "source", "content"} <= set(hits[0])


def test_discover_example():
    cls = discover_example("developer_rag")
    assert cls is QAChatbot
    with pytest.raises(ChainError):
        discover_example("generativeaiexamples_tpu.chains.base")


# ----------------------------------------------------------------- server

def _run(coro):
    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(coro)


async def _with_client(fn):
    ex = _make_example()
    app = create_app(ex, upload_dir=os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "gaie-test-uploads"))
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        await fn(client, ex)
    finally:
        await client.close()


def test_server_health_and_metrics():
    async def fn(client, ex):
        resp = await client.get("/health")
        assert resp.status == 200
        assert (await resp.json())["status"] == "ok"
        resp = await client.get("/metrics")
        assert resp.status == 200
    _run(_with_client(fn))


def test_server_upload_generate_search(tmp_path):
    async def fn(client, ex):
        # upload (reference: server.py:89-118)
        form = aiohttp.FormData()
        form.add_field("file",
                       b"TPU pods scale with ICI. The MXU does matmuls.",
                       filename="notes.txt")
        resp = await client.post("/uploadDocument", data=form)
        assert resp.status == 200, await resp.text()
        assert (await resp.json())["filename"] == "notes.txt"

        # generate with KB → streamed chunks concatenate to the answer
        resp = await client.post("/generate", json={
            "question": "What does the MXU do?",
            "use_knowledge_base": True, "num_tokens": 4000})
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        body = (await resp.read()).decode()
        assert "MXU" in body

        # generate without KB
        resp = await client.post("/generate", json={
            "question": "2+2?", "use_knowledge_base": False,
            "num_tokens": 4000})
        assert "2+2?" in (await resp.read()).decode()

        # documentSearch (reference: server.py:145-159)
        resp = await client.post("/documentSearch", json={
            "content": "matmul unit", "num_docs": 2})
        hits = await resp.json()
        assert isinstance(hits, list) and hits
        assert hits[0]["source"] == "notes.txt"

        # validation error
        resp = await client.post("/generate", json={})
        assert resp.status == 422
    _run(_with_client(fn))


def test_server_pre_stream_error_is_real_http_status():
    """A failure BEFORE the first generated chunk is a real 500 with a
    JSON body + X-Request-ID — not a 200 SSE carrying '[error]' text
    (docs/robustness.md error taxonomy)."""
    class BrokenExample(BaseExample):
        def llm_chain(self, context, question, num_tokens):
            raise RuntimeError("boom")

        def rag_chain(self, prompt, num_tokens):
            raise RuntimeError("boom")

        def ingest_docs(self, data_dir, filename):
            raise RuntimeError("boom")

    async def fn():
        app = create_app(BrokenExample())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            resp = await client.post("/generate", json={
                "question": "x", "num_tokens": 10})
            assert resp.status == 500
            assert resp.headers.get("X-Request-ID")
            body = await resp.json()
            assert "boom" in body["error"]["message"]
            assert body["request_id"] == resp.headers["X-Request-ID"]
        finally:
            await client.close()
    _run(fn())


def test_server_mid_stream_error_degrades_with_event():
    """After chunks have gone out on the 200, a failure keeps the
    in-stream degrade ('[error]' text) and appends a machine-readable
    final event frame."""
    class HalfBrokenExample(BaseExample):
        def llm_chain(self, context, question, num_tokens):
            yield "partial "
            yield "answer"
            raise RuntimeError("mid boom")

        def rag_chain(self, prompt, num_tokens):
            yield from self.llm_chain("", prompt, num_tokens)

        def ingest_docs(self, data_dir, filename):
            pass

    async def fn():
        app = create_app(HalfBrokenExample())
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            resp = await client.post("/generate", json={
                "question": "x", "num_tokens": 10})
            assert resp.status == 200
            body = (await resp.read()).decode()
            assert body.startswith("partial answer")
            assert "[error] mid boom" in body
            event = body.split("event: error\ndata:", 1)[1].strip()
            payload = json.loads(event.split("\n", 1)[0])
            assert payload["message"] == "mid boom"
            assert payload["request_id"] == resp.headers["X-Request-ID"]
        finally:
            await client.close()
    _run(fn())


# ------------------------------------------------------- fused RAG chatbot

def test_developer_rag_fused_path_end_to_end(tmp_path):
    """The chatbot auto-enables fused on-device RAG admission with an
    in-process engine + on-device embedder: fused answers carry source
    attribution, re-ingest does not recompile (stable spec), over-long
    questions fall back to the host path and CLEAR the attribution."""
    import jax
    import jax.numpy as jnp

    from generativeaiexamples_tpu.chains.llm import EngineLLM
    from generativeaiexamples_tpu.embed.encoder import EmbeddingService
    from generativeaiexamples_tpu.engine import Engine, EngineConfig
    from generativeaiexamples_tpu.models import encoder, llama
    from generativeaiexamples_tpu.models.configs import (ENCODER_TINY,
                                                         LLAMA_TINY)
    from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer

    emb = EmbeddingService(
        encoder.init_params(ENCODER_TINY, jax.random.key(1), jnp.float32),
        ENCODER_TINY, ByteTokenizer())
    eng = Engine(
        llama.init_params(LLAMA_TINY, jax.random.key(0), jnp.float32),
        LLAMA_TINY, ByteTokenizer(),
        EngineConfig(max_slots=2, max_input_length=1024,
                     max_output_length=32, prefill_buckets=(128, 512),
                     dtype="float32", page_size=64, kv_pool_tokens=None))
    cfg = from_dict(AppConfig, {
        "text_splitter": {"chunk_size": 100, "chunk_overlap": 20}})
    ex = QAChatbot(llm=EngineLLM(eng), embedder=emb, config=cfg)
    try:
        for i, text in enumerate(["The MXU is a systolic array.",
                                  "ICI links connect TPU chips."]):
            p = tmp_path / f"d{i}.txt"
            p.write_text(text)
            ex.ingest_docs(str(p), f"d{i}.txt")
        assert ex._fused_ready
        spec = ex._fused_spec

        out = "".join(ex.rag_chain("What is the MXU?", 8))
        assert isinstance(out, str)
        assert ex.last_sources, "fused answer lost attribution"

        # another ingest with identical config must keep the spec
        p = tmp_path / "extra.txt"
        p.write_text("Paged KV caching pools pages.")
        ex.ingest_docs(str(p), "extra.txt")
        assert ex._fused_spec == spec

        # over-long question -> host path; attribution cleared
        "".join(ex.rag_chain("why " * 40, 8))
        assert ex.last_sources == []
    finally:
        eng.stop()
