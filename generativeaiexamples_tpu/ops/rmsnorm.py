"""RMSNorm.

Replaces the reference's TRT RMSNorm plugin
(reference: conversion_scripts/llama/build.py:630 ``set_rmsnorm_plugin``).
A plain jnp expression — XLA fuses it into neighboring ops on TPU, so no
Pallas kernel is needed for this one.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rmsnorm(x: jax.Array, weight: jax.Array, eps: float = 1e-5) -> jax.Array:
    """y = x / rms(x) * weight, computed in f32 for stability.

    Matches HF LlamaRMSNorm semantics: variance in float32, scale applied
    in the input dtype.
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y.astype(dtype) * weight.astype(dtype)).astype(dtype)
