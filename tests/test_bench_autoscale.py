"""Tier-1 CPU smoke of the autoscale bench scenario (ISSUE 13): a
short bursty arrival trace through the router over real tiny-engine
replicas, the SLO-driven controller activating parked replicas vs the
equal-average static baseline, and the schema contract for the new
``autoscale`` section (slo_attainment + replica_minutes per arm)."""

import pytest

import jax
import jax.numpy as jnp

import bench
from generativeaiexamples_tpu.engine import Engine, EngineConfig
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import LlamaConfig
from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer
from tools.check_bench_schema import (BenchSchemaError, load_schema,
                                      validate_result)

CFG = LlamaConfig(vocab_size=259 + 5, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  max_position_embeddings=1024)


@pytest.fixture(scope="module")
def engines():
    params = llama.init_params(CFG, jax.random.key(17), dtype=jnp.float32)
    ecfg = EngineConfig(
        max_slots=2, max_input_length=1024, max_output_length=16,
        prefill_buckets=(64,), max_prefill_bucket=64, dtype="float32",
        page_size=16, kv_pool_tokens=4096, max_queue=32,
        steps_per_round=4)
    engs = [Engine(params, CFG, ByteTokenizer(), ecfg) for _ in range(2)]
    yield engs
    for e in engs:
        e.stop()


@pytest.fixture(scope="module")
def autoscale_section(engines):
    # A burst in the middle of a quiet trace, short enough for CPU:
    # the controller observes on a fast cycle so the burst phase can
    # actually trigger a scale-up within the run.
    return bench.run_autoscale_bench(
        engines, duration_s=5.0, trace=((0.25, 1.0), (0.4, 5.0),
                                        (0.35, 1.0)),
        slo_ttft_ms=30000.0, num_tokens=4, min_replicas=1,
        interval_s=0.2, heartbeat_s=0.15, seed=5, prompt_chars=200)


def _synthetic_with(autoscale):
    pipeline = bench.pipeline_snapshot({})
    return bench.assemble_result(
        kind="engine", model="llama-tiny", headline=10.0,
        engine_p50=8.0, engine_p99=12.0, tput=100.0,
        achieved_bw=1e9, bw_util=0.1, bw_steady=True,
        chat=None, e2e_p50=None, e2e_dist=None, e2e_breakdown=None,
        e2e_tps_p50=None, pipeline=pipeline, quant="none", kv_quant=None,
        weights="random-init", prompt_len=16, out_len=4, slots=2,
        steps_per_round=4, kv_pool_pages=8, device="cpu", rtt_ms=None,
        n_devices=1, bench_seconds=1.0, autoscale=autoscale)


def test_parse_trace_normalizes_and_rejects_empty():
    phases = bench.parse_trace("1:2, 1:8, 2:2")
    assert [r for _, r in phases] == [2.0, 8.0, 2.0]
    assert sum(f for f, _ in phases) == pytest.approx(1.0)
    assert phases[0][0] == pytest.approx(0.25)
    with pytest.raises(ValueError):
        bench.parse_trace("  ")


def test_autoscale_bench_end_to_end(autoscale_section):
    section = autoscale_section
    assert section["min_replicas"] == 1
    assert section["max_replicas"] == 2
    labels = [p["policy"] for p in section["policies"]]
    assert labels == ["autoscaled", "static"]
    auto, static = section["policies"]
    for p in section["policies"]:
        assert p["offered"] > 0
        # every offered request landed exactly one outcome row
        assert p["completed"] + p["shed"] + p["errors"] == p["offered"]
        assert 0.0 <= p["slo_attainment"] <= 1.0
        assert p["replica_minutes"] > 0
        assert 1.0 <= p["avg_replicas"] <= 2.0
    # the controller actually acted: the burst produced a scale-up and
    # a decision ring (the acceptance criterion's evidence path)
    assert auto["scale_ups"] >= 1
    assert auto["decisions"] > 0
    assert auto["peak_replicas"] == 2
    # the static arm is the honest equal-average baseline: sized from
    # the autoscaled arm's average, never above the ceiling
    assert static["replicas_static"] == max(
        1, min(2, round(auto["avg_replicas"])))
    assert static["scale_ups"] == 0 and static["decisions"] == 0
    # nothing 5xx'd in either arm — overload shows as shed, not failure
    assert auto["errors"] == 0 and static["errors"] == 0


def test_autoscale_section_schema_valid(autoscale_section):
    validate_result(_synthetic_with(autoscale_section))
    validate_result(_synthetic_with(None))   # autoscale-less runs pass


def test_autoscale_section_matches_schema_keys(autoscale_section):
    schema = load_schema()
    assert set(autoscale_section) == set(schema["autoscale"])
    for p in autoscale_section["policies"]:
        assert set(p) == set(schema["autoscale_policy"])


def test_autoscale_policy_field_rename_fails_fast(autoscale_section):
    import copy
    section = copy.deepcopy(autoscale_section)
    section["policies"][0]["minutes"] = \
        section["policies"][0].pop("replica_minutes")
    with pytest.raises(BenchSchemaError, match="autoscale.policies"):
        validate_result(_synthetic_with(section))
