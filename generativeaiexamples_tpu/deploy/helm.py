"""Helm chart renderer for the template subset the first-party charts use.

The reference renders its charts through the Helm Go SDK inside the
operator (reference: deploy/k8s-operator/kube-trailblazer/pkg/helmer/
helmer.go:237 ``InstallOrUpgradePackage``). This image has no Go/helm
binary, so the operator renders charts with this engine instead. The
supported subset is valid Helm syntax — the charts also render with real
``helm template`` unchanged:

- ``{{ .Values.a.b }}``, ``{{ .Release.Name }}``, ``{{ .Release.Namespace }}``,
  ``{{ .Chart.Name }}``, ``{{ .Chart.Version }}``
- pipes: ``| default <literal>``, ``| quote``, ``| int``, ``| toYaml``,
  ``| nindent N``, ``| sha256sum`` (sprig parity, checksum annotations)
- blocks: ``{{- if <ref> }} ... {{- else }} ... {{- end }}`` and
  ``{{- if not <ref> }}`` (nestable, truthiness like Helm:
  absent/None/False/0/""/empty map are false)
- ``{{- range .Values.list }}`` with ``{{ . }}`` for the element
- ``{{- fail "message" }}`` aborts the render (value validation)

Charts live as plain directories: ``Chart.yaml``, ``values.yaml``,
``templates/*.yaml``.
"""

from __future__ import annotations

import hashlib
import os
import re
from dataclasses import dataclass, field
from typing import Any, Optional

import yaml

from ..utils.errors import ConfigError


class ChartError(ConfigError):
    """Chart loading/rendering failure."""


@dataclass
class Chart:
    name: str
    version: str
    path: str
    values: dict = field(default_factory=dict)
    templates: dict[str, str] = field(default_factory=dict)


def load_chart(path: str) -> Chart:
    meta_path = os.path.join(path, "Chart.yaml")
    if not os.path.isfile(meta_path):
        raise ChartError(f"no Chart.yaml in {path}")
    with open(meta_path) as f:
        meta = yaml.safe_load(f) or {}
    values: dict = {}
    vpath = os.path.join(path, "values.yaml")
    if os.path.isfile(vpath):
        with open(vpath) as f:
            values = yaml.safe_load(f) or {}
    templates: dict[str, str] = {}
    tdir = os.path.join(path, "templates")
    if os.path.isdir(tdir):
        for fname in sorted(os.listdir(tdir)):
            if fname.endswith((".yaml", ".yml")):
                with open(os.path.join(tdir, fname)) as f:
                    templates[fname] = f.read()
    return Chart(name=str(meta.get("name", os.path.basename(path))),
                 version=str(meta.get("version", "0.0.0")),
                 path=path, values=values, templates=templates)


def deep_merge(base: dict, override: dict) -> dict:
    """Helm's values merge: override wins, dicts merge recursively."""
    out = dict(base)
    for k, v in (override or {}).items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = deep_merge(out[k], v)
        else:
            out[k] = v
    return out


_SENTINEL = object()


def _lookup(ctx: dict, dotted: str) -> Any:
    cur: Any = ctx
    for part in dotted.lstrip(".").split("."):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        else:
            return _SENTINEL
    return cur


def _truthy(v: Any) -> bool:
    if v is _SENTINEL or v is None:
        return False
    if isinstance(v, (dict, list, str)):
        return len(v) > 0
    return bool(v)


_PIPE_RE = re.compile(r"\s*\|\s*")
_TAG_RE = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}")


def _apply_pipe(value: Any, pipe: str) -> Any:
    pipe = pipe.strip()
    if pipe.startswith("default "):
        arg = pipe[len("default "):].strip()
        literal = yaml.safe_load(arg)
        return literal if (value is _SENTINEL or value is None) else value
    if pipe == "quote":
        v = "" if value in (_SENTINEL, None) else value
        return '"' + str(v).replace('"', '\\"') + '"'
    if pipe == "int":
        return int(value) if value not in (_SENTINEL, None) else 0
    if pipe == "toYaml":
        return yaml.safe_dump(value, default_flow_style=False).rstrip()
    if pipe == "sha256sum":  # sprig parity: checksum annotations
        v = "" if value in (_SENTINEL, None) else str(value)
        return hashlib.sha256(v.encode()).hexdigest()
    m = re.match(r"nindent (\d+)$", pipe)
    if m:
        pad = " " * int(m.group(1))
        text = "" if value in (_SENTINEL, None) else str(value)
        return "\n" + "\n".join(pad + line for line in text.splitlines())
    raise ChartError(f"unsupported template pipe {pipe!r}")


def _eval_expr(expr: str, ctx: dict) -> Any:
    parts = _PIPE_RE.split(expr)
    head = parts[0].strip()
    if head.startswith("."):
        value = _lookup(ctx, head)
    else:
        value = yaml.safe_load(head)  # literal
    for pipe in parts[1:]:
        value = _apply_pipe(value, pipe)
    if value is _SENTINEL:
        raise ChartError(f"unresolved template reference {head!r}")
    return value


@dataclass
class _Block:
    kind: str            # "text" | "expr" | "if" | "range"
    payload: Any = None
    children: list = field(default_factory=list)
    alt: list = field(default_factory=list)   # else branch


def _parse(src: str) -> list[_Block]:
    """Parse template source into a block tree."""
    blocks: list[_Block] = []
    stack: list[_Block] = []

    def emit(b: _Block) -> None:
        (stack[-1].alt if stack and getattr(stack[-1], "_in_else", False)
         else stack[-1].children if stack else blocks).append(b)

    pos = 0
    for m in _TAG_RE.finditer(src):
        text = src[pos:m.start()]
        # trim semantics: "{{-" eats preceding whitespace+newline
        if m.group(0).startswith("{{-"):
            text = text.rstrip(" \t")
            if text.endswith("\n"):
                text = text[:-1]
        if text:
            emit(_Block("text", text))
        tag = m.group(1)
        if tag.startswith("if "):
            b = _Block("if", tag[3:].strip())
            emit(b)
            stack.append(b)
        elif tag == "else":
            if not stack or stack[-1].kind != "if":
                raise ChartError("'else' outside if")
            stack[-1]._in_else = True  # type: ignore[attr-defined]
        elif tag.startswith("range "):
            b = _Block("range", tag[6:].strip())
            emit(b)
            stack.append(b)
        elif tag.startswith("fail "):
            # helm's fail: abort the whole render with a message (used to
            # refuse insecure value combinations at template time)
            emit(_Block("fail", str(yaml.safe_load(tag[5:].strip()))))
        elif tag == "end":
            if not stack:
                raise ChartError("'end' without open block")
            stack.pop()
        else:
            emit(_Block("expr", tag))
        pos = m.end()
        if m.group(0).endswith("-}}"):
            while pos < len(src) and src[pos] in " \t":
                pos += 1
            if pos < len(src) and src[pos] == "\n":
                pos += 1
    if src[pos:]:
        emit(_Block("text", src[pos:]))
    if stack:
        raise ChartError("unclosed template block")
    return blocks


def _render_blocks(blocks: list[_Block], ctx: dict) -> str:
    out: list[str] = []
    for b in blocks:
        if b.kind == "text":
            out.append(b.payload)
        elif b.kind == "expr":
            out.append(str(_eval_expr(b.payload, ctx)))
        elif b.kind == "if":
            expr = b.payload
            negate = expr.startswith("not ")
            if negate:
                expr = expr[4:].strip()
            cond = _lookup(ctx, expr) if expr.startswith(".") \
                else yaml.safe_load(expr)
            truthy = _truthy(cond) ^ negate
            branch = b.children if truthy else b.alt
            out.append(_render_blocks(branch, ctx))
        elif b.kind == "fail":
            raise ChartError(f"fail: {b.payload}")
        elif b.kind == "range":
            items = _lookup(ctx, b.payload)
            if items is _SENTINEL or items is None:
                items = []
            for item in items:
                sub = dict(ctx)
                sub[""] = item  # "{{ . }}" resolves via the "" key
                out.append(_render_blocks(b.children, sub))
    return "".join(out)


def render_chart(chart: Chart, release_name: str, namespace: str = "default",
                 values: Optional[dict] = None) -> list[dict]:
    """Render every template with merged values; returns parsed manifests
    (the ``helm template`` equivalent)."""
    merged = deep_merge(chart.values, values or {})
    ctx = {
        "Values": merged,
        "Release": {"Name": release_name, "Namespace": namespace},
        "Chart": {"Name": chart.name, "Version": chart.version},
    }
    objects: list[dict] = []
    for fname, src in chart.templates.items():
        try:
            text = _render_blocks(_parse(src), ctx)
        except ChartError as exc:
            raise ChartError(f"{chart.name}/templates/{fname}: {exc}") from exc
        for doc in yaml.safe_load_all(text):
            if isinstance(doc, dict) and doc:
                objects.append(doc)
            elif doc not in (None, ""):
                raise ChartError(
                    f"{chart.name}/templates/{fname}: non-mapping manifest")
    return objects
