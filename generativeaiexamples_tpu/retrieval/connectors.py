"""External vector-engine connectors (Milvus, pgvector), import-gated.

Parity with the reference's external stores (reference:
common/utils.py:143-225 — Milvus via llama-index/langchain wrappers with a
GPU_IVF_FLAT index, pgvector with DB auto-create at utils.py:157-164).
The client libraries (pymilvus, psycopg2) are not baked into this image, so
both classes import lazily and raise a clear error; the interface matches
``VectorStore`` exactly, so swapping engines is a config change
(``get_vector_store("milvus", url=...)``).
"""

from __future__ import annotations

import re
from typing import Sequence

import numpy as np

from ..utils.errors import ConfigError
from .store import SearchHit, VectorStore, _as_2d


class MilvusStore(VectorStore):
    """Milvus collection with IVF_FLAT (nlist/nprobe parity).

    reference: common/utils.py:181-186 builds GPU_IVF_FLAT nlist=64 and
    searches nprobe=16; CPU IVF_FLAT here — on TPU systems the accelerated
    path is the first-party ``exact-tpu`` store instead.
    """

    def __init__(self, dim: int, url: str = "http://localhost:19530",
                 collection: str = "rag", metric: str = "ip",
                 nlist: int = 64, nprobe: int = 16):
        try:
            from pymilvus import MilvusClient  # noqa: F401
        except ImportError as exc:
            raise ConfigError(
                "MilvusStore requires the 'pymilvus' package (not installed "
                "in this image). Use get_vector_store('exact'|'ivfflat') or "
                "install pymilvus.") from exc
        self._dim = dim
        self.metric = metric
        self.nprobe = nprobe
        self._client = MilvusClient(uri=url)
        self._collection = collection
        if not self._client.has_collection(collection):
            # auto_id: Milvus assigns primary keys, so reconnecting to an
            # existing collection can never collide with prior inserts.
            self._client.create_collection(
                collection_name=collection, dimension=dim, auto_id=True,
                metric_type="IP" if metric == "ip" else "L2",
                index_params={"index_type": "IVF_FLAT",
                              "params": {"nlist": nlist}})

    @property
    def dim(self) -> int:
        return self._dim

    def __len__(self) -> int:
        stats = self._client.get_collection_stats(self._collection)
        return int(stats["row_count"])

    def add(self, embeddings: np.ndarray) -> list[int]:
        emb = _as_2d(embeddings)
        res = self._client.insert(self._collection, [
            {"vector": row.tolist()} for row in emb])
        return [int(i) for i in res["ids"]]

    def search(self, queries: np.ndarray, k: int = 4) -> list[list[SearchHit]]:
        q = _as_2d(queries)
        res = self._client.search(
            self._collection, data=q.tolist(), limit=k,
            search_params={"params": {"nprobe": self.nprobe}})
        return [[SearchHit(int(h["id"]), float(h["distance"])) for h in row]
                for row in res]

    def delete(self, ids: Sequence[int]) -> None:
        self._client.delete(self._collection, ids=list(ids))

    def save(self, path: str) -> None:  # server-side persistence
        self._client.flush(self._collection)

    @classmethod
    def load(cls, path: str) -> "MilvusStore":
        raise NotImplementedError("MilvusStore persists server-side")


class PgvectorStore(VectorStore):
    """Postgres + pgvector table. Auto-creates the database and table the
    way the reference does (reference: common/utils.py:157-164)."""

    def __init__(self, dim: int, url: str = "postgresql://localhost:5432",
                 table: str = "rag_vectors", metric: str = "ip"):
        try:
            import psycopg2  # noqa: F401
        except ImportError as exc:
            raise ConfigError(
                "PgvectorStore requires 'psycopg2' (not installed in this "
                "image). Use get_vector_store('exact'|'ivfflat') or install "
                "psycopg2.") from exc
        import psycopg2
        if not re.match(r"^[A-Za-z_][A-Za-z0-9_]*$", table):
            # the table name is interpolated into SQL below — reject
            # anything that isn't a plain identifier (injection guard)
            raise ConfigError(f"invalid pgvector table name {table!r}")
        self._dim = dim
        self.metric = metric
        self._table = table
        self._conn = psycopg2.connect(url)
        self._conn.autocommit = True
        with self._conn.cursor() as cur:
            cur.execute("CREATE EXTENSION IF NOT EXISTS vector")
            cur.execute(
                f"CREATE TABLE IF NOT EXISTS {table} "
                f"(id BIGSERIAL PRIMARY KEY, embedding vector({dim}))")

    @property
    def dim(self) -> int:
        return self._dim

    def __len__(self) -> int:
        with self._conn.cursor() as cur:
            cur.execute(f"SELECT COUNT(*) FROM {self._table}")
            return int(cur.fetchone()[0])

    def add(self, embeddings: np.ndarray) -> list[int]:
        emb = _as_2d(embeddings)
        ids = []
        with self._conn.cursor() as cur:
            for row in emb:
                cur.execute(
                    f"INSERT INTO {self._table} (embedding) VALUES (%s) "
                    f"RETURNING id", (row.tolist(),))
                ids.append(int(cur.fetchone()[0]))
        return ids

    def search(self, queries: np.ndarray, k: int = 4) -> list[list[SearchHit]]:
        q = _as_2d(queries)
        op = "<#>" if self.metric == "ip" else "<->"  # negative ip / l2 dist
        out = []
        with self._conn.cursor() as cur:
            for row in q:
                cur.execute(
                    f"SELECT id, embedding {op} %s::vector AS d "
                    f"FROM {self._table} ORDER BY d LIMIT %s",
                    (row.tolist(), k))
                # Match the VectorStore score contract: ip → inner product
                # (pgvector's <#> is its negation), l2 → negated *squared*
                # distance (<-> is euclidean), so scores are comparable
                # across every backend.
                if self.metric == "ip":
                    hits = [SearchHit(int(i), -float(d))
                            for i, d in cur.fetchall()]
                else:
                    hits = [SearchHit(int(i), -float(d) ** 2)
                            for i, d in cur.fetchall()]
                out.append(hits)
        return out

    def delete(self, ids: Sequence[int]) -> None:
        with self._conn.cursor() as cur:
            cur.execute(f"DELETE FROM {self._table} WHERE id = ANY(%s)",
                        (list(ids),))

    def save(self, path: str) -> None:  # server-side persistence
        pass

    @classmethod
    def load(cls, path: str) -> "PgvectorStore":
        raise NotImplementedError("PgvectorStore persists server-side")
