"""LangChain connector classes for the TPU serving stack.

The published integration surface of the reference is a LangChain ``LLM``
subclass over Triton gRPC plus embeddings classes (reference:
integrations/langchain/llms/triton_trt_llm.py:48 ``TensorRTLLM(LLM)``,
integrations/langchain/embeddings/nemo_embed.py). ``TpuLLM`` /
``TpuEmbeddings`` play those roles against this framework's endpoints:

- ``mode="grpc"``  — the native LLMService (serving/grpc_server.py), the
  analogue of the reference's default GrpcTritonClient on :8001;
- ``mode="http"``  — the OpenAI-compatible ``/v1`` API
  (serving/openai_api.py).

When langchain-core is installed the classes are real LangChain
components (work in LCEL chains); otherwise they derive from minimal
structural stand-ins with the same contract, so the connector logic works
and tests run without the dependency.
"""

from __future__ import annotations

from typing import Any, Iterator, List, Optional

try:  # real LangChain base classes when available
    from langchain_core.callbacks import CallbackManagerForLLMRun
    from langchain_core.embeddings import Embeddings as _LCEmbeddings
    from langchain_core.language_models.llms import LLM as _LCLLM
    from langchain_core.outputs import GenerationChunk
    HAVE_LANGCHAIN = True
except ImportError:  # structural stand-ins (same method contracts)
    HAVE_LANGCHAIN = False
    CallbackManagerForLLMRun = Any  # type: ignore[assignment,misc]

    class GenerationChunk:  # type: ignore[no-redef]
        def __init__(self, text: str):
            self.text = text

    class _LCLLM:  # type: ignore[no-redef]
        """Contract subset of langchain_core LLM: invoke/stream drive
        _call/_stream. Pydantic field declaration degrades to kwargs."""

        def __init__(self, **kwargs: Any):
            for k, v in kwargs.items():
                setattr(self, k, v)

        def invoke(self, prompt: str, stop: Optional[List[str]] = None,
                   **kw: Any) -> str:
            return self._call(prompt, stop=stop, **kw)

        def stream(self, prompt: str, stop: Optional[List[str]] = None,
                   **kw: Any) -> Iterator[str]:
            for chunk in self._stream(prompt, stop=stop, **kw):
                yield chunk.text

    class _LCEmbeddings:  # type: ignore[no-redef]
        def __init__(self, **kwargs: Any):
            for k, v in kwargs.items():
                setattr(self, k, v)


STOP_WORDS = ["</s>"]  # reference connector default, triton_trt_llm.py:45


class TpuLLM(_LCLLM):
    """LangChain LLM over the TPU serving stack.

    Parameters mirror the reference connector's
    (triton_trt_llm.py:66-79): server_url, model_name, temperature,
    top_p, top_k, tokens, beam_width, repetition_penalty, length_penalty,
    streaming.
    """

    server_url: str = ""
    model_name: str = "ensemble"
    mode: str = "grpc"               # "grpc" | "http"
    temperature: float = 1.0
    top_p: float = 0.0
    top_k: int = 1
    tokens: int = 100
    beam_width: int = 1
    repetition_penalty: float = 1.0
    length_penalty: float = 1.0
    streaming: bool = True
    timeout: float = 120.0

    # pydantic v2 (real langchain) allows arbitrary private attrs via
    # model_config; the stand-in just sets attributes.
    model_config = {"arbitrary_types_allowed": True, "extra": "allow"}

    @property
    def _llm_type(self) -> str:
        return "tpu_llm"

    @property
    def _identifying_params(self) -> dict:
        return {"server_url": self.server_url, "model_name": self.model_name,
                "mode": self.mode}

    @property
    def _default_params(self) -> dict:
        return {"max_tokens": self.tokens, "temperature": self.temperature,
                "top_k": self.top_k, "top_p": self.top_p,
                "repetition_penalty": self.repetition_penalty,
                "length_penalty": self.length_penalty,
                "beam_width": self.beam_width}

    def _grpc(self):
        client = getattr(self, "_grpc_client", None)
        if client is None:
            from ..serving.grpc_server import GrpcLLMClient
            client = GrpcLLMClient(self.server_url, timeout=self.timeout)
            object.__setattr__(self, "_grpc_client", client)
        return client

    def _http(self):
        client = getattr(self, "_http_client", None)
        if client is None:
            from ..chains.llm import OpenAICompatLLM
            client = OpenAICompatLLM(self.server_url, self.model_name,
                                     timeout=self.timeout)
            object.__setattr__(self, "_http_client", client)
        return client

    def _merged(self, stop: Optional[List[str]], kwargs: dict) -> dict:
        params = {**self._default_params, **kwargs}
        params["stop_words"] = list(stop if stop is not None else STOP_WORDS)
        return params

    def _call(self, prompt: str, stop: Optional[List[str]] = None,
              run_manager: Optional[CallbackManagerForLLMRun] = None,
              **kwargs: Any) -> str:
        return "".join(c.text for c in
                       self._stream(prompt, stop=stop, **kwargs))

    def _stream(self, prompt: str, stop: Optional[List[str]] = None,
                run_manager: Optional[CallbackManagerForLLMRun] = None,
                **kwargs: Any) -> Iterator[GenerationChunk]:
        p = self._merged(stop, kwargs)
        if self.mode == "grpc":
            it = self._grpc().generate_stream(
                prompt, max_tokens=p["max_tokens"],
                temperature=p["temperature"], top_k=p["top_k"],
                top_p=p["top_p"],
                repetition_penalty=p["repetition_penalty"],
                length_penalty=p["length_penalty"],
                beam_width=p["beam_width"], stop_words=p["stop_words"],
                bad_words=list(p.get("bad_words", [])))
        else:
            # The OpenAI-compatible surface carries no penalty/ban
            # fields; silently differing from mode="grpc" would be worse
            # than refusing.
            unsupported = {
                "repetition_penalty": (p["repetition_penalty"], 1.0),
                "length_penalty": (p["length_penalty"], 1.0),
                "beam_width": (p["beam_width"], 1),
                "bad_words": (list(p.get("bad_words", [])), []),
            }
            bad = [k for k, (v, default) in unsupported.items()
                   if v != default]
            if bad:
                raise ValueError(
                    f"mode='http' does not support {bad}; use mode='grpc'")
            it = self._http().stream(
                prompt, max_tokens=p["max_tokens"], stop=p["stop_words"],
                temperature=p["temperature"], top_k=p["top_k"],
                top_p=p["top_p"])
        for text in it:
            chunk = GenerationChunk(text=text)
            if run_manager is not None and HAVE_LANGCHAIN:
                run_manager.on_llm_new_token(text, chunk=chunk)
            yield chunk


class TpuJobsLLM(_LCLLM):
    """LangChain LLM over the async job API (submit-then-poll).

    The client-side counterpart of the reference's cloud-function
    connector (nv_aiplay.py:222-316): generation goes through
    POST /v1/jobs + 202 polling via ``serving.client.JobsClient``, which
    survives load-balancer/request timeouts that kill a streaming call.
    ``model_name`` resolves against the server's /v1/models registry
    with exact-then-substring matching, as the reference resolves NVCF
    function names. No token streaming — per-chunk delivery is what the
    job API exists to avoid; use ``TpuLLM`` for streaming.
    """

    server_url: str = ""
    model_name: str = ""             # "" = server default; else resolved
    temperature: float = 1.0
    top_p: float = 0.0
    top_k: int = 1
    tokens: int = 100
    timeout: float = 300.0
    poll_interval: float = 0.25

    model_config = {"arbitrary_types_allowed": True, "extra": "allow"}

    @property
    def _llm_type(self) -> str:
        return "tpu_jobs_llm"

    @property
    def _identifying_params(self) -> dict:
        return {"server_url": self.server_url,
                "model_name": self.model_name}

    def _client(self):
        client = getattr(self, "_jobs_client", None)
        if client is None:
            from ..serving.client import JobsClient
            client = JobsClient(self.server_url, timeout=self.timeout,
                                poll_interval=self.poll_interval)
            if self.model_name:
                # resolve against the GENERATION entries only (the
                # registry also lists the embeddings pseudo-model) and
                # remember the result — it is sent with every job
                models = {k: v for k, v in client.available_models().items()
                          if k != "embeddings"}
                name = self.model_name
                resolved = name if name in models else next(
                    (k for k in sorted(models) if name in k), None)
                if resolved is None:
                    raise ValueError(
                        f"unknown model name {name!r}; server has "
                        f"{sorted(models)}")
                object.__setattr__(self, "_resolved_model", resolved)
            object.__setattr__(self, "_jobs_client", client)
        return client

    def _call(self, prompt: str, stop: Optional[List[str]] = None,
              run_manager: Optional[CallbackManagerForLLMRun] = None,
              **kwargs: Any) -> str:
        client = self._client()
        params = {"max_tokens": self.tokens, "temperature": self.temperature,
                  "top_k": self.top_k, "top_p": self.top_p, **kwargs}
        resolved = getattr(self, "_resolved_model", "")
        if resolved:
            params["model"] = resolved
        if stop is not None:
            params["stop"] = list(stop)
        return client.generate(prompt, **params)


class TpuEmbeddings(_LCEmbeddings):
    """LangChain Embeddings over the stack's encoder endpoints, with the
    passage/query input-type split of the reference's NeMo embedder
    (reference: integrations/langchain/embeddings/nemo_embed.py:96-102)."""

    server_url: str = ""
    mode: str = "grpc"               # "grpc" | "http" (/v1/embeddings)
    model_name: str = "e5-large-v2"
    timeout: float = 60.0

    model_config = {"arbitrary_types_allowed": True, "extra": "allow"}

    def _grpc(self):
        client = getattr(self, "_grpc_client", None)
        if client is None:
            from ..serving.grpc_server import GrpcLLMClient
            client = GrpcLLMClient(self.server_url, timeout=self.timeout)
            object.__setattr__(self, "_grpc_client", client)
        return client

    def _embed_http(self, texts: List[str], input_type: str):
        import requests
        url = self.server_url.rstrip("/") + "/v1/embeddings"
        resp = requests.post(url, json={
            "model": self.model_name, "input": texts,
            "input_type": input_type}, timeout=self.timeout)
        resp.raise_for_status()
        data = sorted(resp.json()["data"], key=lambda d: d["index"])
        return [d["embedding"] for d in data]

    def embed_documents(self, texts: List[str]) -> List[List[float]]:
        if self.mode == "grpc":
            return self._grpc().embed(texts, "passage").tolist()
        return self._embed_http(texts, "passage")

    def embed_query(self, text: str) -> List[float]:
        if self.mode == "grpc":
            return self._grpc().embed([text], "query")[0].tolist()
        return self._embed_http([text], "query")[0]
