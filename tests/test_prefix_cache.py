"""Shared-prefix KV cache tests.

Host-side unit coverage (block hashing, trie match, refcounts, COW
demotion, LRU leaf-first eviction — no device needed) plus engine-level
serving tests on the CPU backend: a warm request must produce EXACTLY
the cold path's tokens while skipping prefill for the cached prefix
(``prefix_cache_hit_tokens``), and eviction under pool pressure must
never strand pages.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from generativeaiexamples_tpu.engine import (Engine, EngineConfig,
                                             SamplingParams)
from generativeaiexamples_tpu.engine.prefix_cache import (
    PrefixCache, hash_blocks, usable_prefix_tokens)
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import LlamaConfig
from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer

PAGE = 16

CFG = LlamaConfig(vocab_size=259 + 5, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  max_position_embeddings=256)


# --------------------------------------------------------------- unit level

def test_hash_blocks_full_blocks_only_and_chaining():
    toks = list(range(40))
    hashes = hash_blocks(toks, PAGE)
    assert len(hashes) == 2          # the 8-token tail is not hashable
    # identical prefix -> identical chain
    assert hash_blocks(toks[:32], PAGE) == hashes
    # a change in block 0 reaches block 1 through the parent chain
    other = hash_blocks([1] + toks[1:], PAGE)
    assert other[0] != hashes[0] and other[1] != hashes[1]
    # position matters: the same 16 tokens as block 1 hash differently
    assert hash_blocks(toks[:16], PAGE)[0] != \
        hash_blocks(toks[16:32] + toks[16:32], PAGE)[1]


def test_usable_prefix_tokens_cow_cap():
    assert usable_prefix_tokens(0, 40, PAGE) == 0
    assert usable_prefix_tokens(2, 40, PAGE) == 32    # tail is uncached
    assert usable_prefix_tokens(1, 17, PAGE) == 16    # 1 token to prefill
    # full cover: capped one block short so >= 1 token runs through
    # prefill (COW demotion — the tail block gets a private page)
    assert usable_prefix_tokens(2, 32, PAGE) == 16
    assert usable_prefix_tokens(1, 16, PAGE) == 0


def _chain(cache: PrefixCache, toks, pages):
    hashes = hash_blocks(toks, PAGE)
    assert len(hashes) == len(pages)
    for i, (h, p) in enumerate(zip(hashes, pages)):
        assert cache.insert(h, hashes[i - 1] if i else None, p)
    return hashes


def test_match_acquire_release_refcount_lifecycle():
    cache = PrefixCache(PAGE)
    toks = list(range(48))
    hashes = _chain(cache, toks, [1, 2, 3])
    assert cache.match(hashes) == 3
    assert cache.match(hash_blocks([9] * 48, PAGE)) == 0
    assert cache.acquire(hashes[:2]) == [1, 2]
    # refcounts (registrant's + ours) pin every page: nothing evictable
    assert cache.evict(10) == []
    cache.release(hashes[:2])
    cache.release(hashes)        # registrant retires too
    assert cache.owns(2)         # refcount 0 but still resident (warm)
    assert cache.cached_pages == 3
    # reclaim walks leaf-first so surviving chains stay walkable
    assert cache.evict(2) == [3, 2]
    assert cache.match(hashes) == 1
    assert cache.evict(5) == [1]
    assert cache.cached_pages == 0


def test_eviction_is_lru_across_chains():
    cache = PrefixCache(PAGE)
    ha = _chain(cache, list(range(32)), [1, 2])
    hb = _chain(cache, list(range(100, 116)), [3])
    cache.release(ha)            # A idle first -> older tick
    cache.release(hb)
    assert cache.evict(1) == [2]     # A's leaf, LRU
    assert cache.evict(2) == [1, 3]  # then A's root, then B


def test_evict_never_rescans_entries():
    """The evictable-leaf heap is maintained incrementally (pushed on
    release-to-zero / last-child-gone, lazily invalidated): evict()
    must do NO full scan of the entry table, however many times it is
    called in warm steady state. Pinned by swapping the entry dict for
    one whose iteration paths raise."""
    cache = PrefixCache(PAGE)
    released = []
    for r in range(50):
        toks = [(r * 97 + i) % 250 + 3 for i in range(32)]
        h = _chain(cache, toks, [2 * r + 1, 2 * r + 2])
        cache.release(h)
        released.append(h)

    class NoScanDict(dict):
        def __iter__(self):
            raise AssertionError("evict iterated _entries")

        def items(self):
            raise AssertionError("evict scanned _entries.items()")

        def keys(self):
            raise AssertionError("evict scanned _entries.keys()")

        def values(self):
            raise AssertionError("evict scanned _entries.values()")

    cache._entries = NoScanDict(cache._entries)
    freed = []
    for _ in range(30):   # one eviction per admission, steady state
        freed += cache.evict(1)
    assert len(freed) == 30
    # LRU leaf-first order intact: chain r's leaf (2r+2) before its
    # root (2r+1), chains in release (tick) order
    assert freed[:6] == [2, 1, 4, 3, 6, 5]
    # lazy invalidation: re-acquiring makes heap copies stale, a later
    # release re-arms eviction at the NEW recency
    live = released[20]
    # plain dict again (unbound dict.items bypasses the raising
    # overrides — this is test scaffolding, not evict behavior)
    cache._entries = {k: v for k, v in dict.items(cache._entries)}
    cache.acquire(live)
    assert cache.evict(2) != []             # skips the stale entries
    cache.release(live)
    # next LRU chain evicts; the re-released chain 20 moved to the
    # BACK of the LRU (new tick) — its stale heap copies are skipped
    assert set(cache.evict(2)) == {33, 34}
    rest = cache.evict(1000)
    assert rest[-2:] == [42, 41]            # chain 20 last, leaf-first
    assert cache.cached_pages == 0


def test_evict_sink_sees_victims_before_removal():
    cache = PrefixCache(PAGE)
    h = _chain(cache, list(range(32)), [1, 2])
    cache.release(h)
    seen = []
    cache.evict(2, sink=lambda hh, e: seen.append((hh, e.page, e.parent)))
    assert [(s[1], s[2]) for s in seen] == [(2, h[0]), (1, None)]


def test_remove_demotes_only_reclaimable_blocks():
    cache = PrefixCache(PAGE)
    h = _chain(cache, list(range(48)), [1, 2, 3])
    assert cache.remove(h[0]) is None      # has children
    assert cache.remove(h[2]) is None      # still referenced (refcount 1)
    cache.release(h)
    assert cache.remove(h[2]) == 3         # leaf-first works
    assert cache.remove(h[1]) == 2
    assert cache.remove(h[0]) == 1
    assert cache.remove(h[0]) is None      # gone
    assert cache.cached_pages == 0
    assert cache.stats.evicted_pages == 0  # demotion is not an eviction


def test_insert_dedup_keeps_page_private():
    cache = PrefixCache(PAGE)
    hashes = _chain(cache, list(range(16)), [1])
    assert cache.insert(hashes[0], None, 7) is False
    assert not cache.owns(7)     # duplicate block: caller keeps page 7
    assert cache.cached_pages == 1


# ------------------------------------------------------------- engine level

def _build(prompt_cap=None, pool_tokens=None, prefix=True, kv_quant="",
           max_in=128, key=31):
    params = llama.init_params(CFG, jax.random.key(key), dtype=jnp.float32)
    cfg = EngineConfig(max_slots=2, max_input_length=max_in,
                       max_output_length=16, prefill_buckets=(32, 64),
                       page_size=PAGE, dtype="float32",
                       kv_pool_tokens=pool_tokens, steps_per_round=4,
                       max_prefill_bucket=prompt_cap, prefix_cache=prefix,
                       kv_quant=kv_quant)
    return Engine(params, CFG, ByteTokenizer(), cfg), params


def _greedy_reference(params, prompt_ids, n_steps):
    ids = list(prompt_ids)
    for _ in range(n_steps):
        tokens = jnp.asarray(np.asarray(ids, np.int32)[None, :])
        pos = jnp.arange(len(ids), dtype=jnp.int32)[None, :]
        logits, _ = llama.apply(params, CFG, tokens, pos)
        ids.append(int(jnp.argmax(logits[0, -1])))
    return ids[len(prompt_ids):]


SP = SamplingParams(max_tokens=6, top_k=1, ignore_eos=True)


def _pages_conserved(eng):
    cached = eng._prefix_cache.cached_pages if eng._prefix_cache else 0
    return len(eng._free_pages) + cached == eng._n_pages - 1


def test_shared_prefix_hit_parity_with_cold_path():
    eng, params = _build()
    prompt_a = [(i * 7) % 250 + 3 for i in range(40)]
    prompt_b = prompt_a[:32] + [(i * 13) % 250 + 3 for i in range(9)]
    with eng:
        a = eng.submit(prompt_a, SP)
        a.text()
        assert eng.stats["prefix_cache_hit_tokens"] == 0
        b = eng.submit(prompt_b, SP)     # shares A's first 2 blocks
        b.text()
    stats = eng.stats
    assert stats["prefix_cache_hit_tokens"] == 32
    assert 0 < stats["prefix_cache_hit_rate"] < 1
    # token-level parity with the uncached path (pure forward)
    assert a.token_ids == _greedy_reference(params, prompt_a, 6)
    assert b.token_ids == _greedy_reference(params, prompt_b, 6)
    assert _pages_conserved(eng)


def test_identical_resubmission_cow_demotes_tail_block():
    """A fully cached, page-aligned prompt still prefills its last block
    (at least one token must produce logits): the shared tail page is
    NOT mapped — COW demotion gives that logical block a private page —
    and output parity holds."""
    eng, params = _build()
    prompt = [(i * 11) % 250 + 3 for i in range(32)]   # exactly 2 blocks
    with eng:
        a = eng.submit(prompt, SP)
        a.text()
        b = eng.submit(prompt, SP)
        b.text()
    assert eng.stats["prefix_cache_hit_tokens"] == 16  # capped, not 32
    assert a.token_ids == b.token_ids == _greedy_reference(params, prompt, 6)
    assert _pages_conserved(eng)


def test_multi_chunk_hit_after_long_prompt_admission():
    """Prefix hits compose with chunked long-prompt serving: a 98-token
    prompt sharing 48 tokens with a cached 80-token one admits as two
    suffix chunks (seeded seen mask + accumulate) and matches the pure
    forward exactly."""
    eng, params = _build(prompt_cap=32)
    prompt_a = [(i * 7) % 250 + 3 for i in range(80)]
    prompt_b = prompt_a[:48] + [(i * 5) % 250 + 3 for i in range(50)]
    with eng:
        a = eng.submit(prompt_a, SP)     # cold chunked admission
        a.text()
        b = eng.submit(prompt_b, SP)
        b.text()
    assert eng.stats["prefix_cache_hit_tokens"] == 48
    assert a.token_ids == _greedy_reference(params, prompt_a, 6)
    assert b.token_ids == _greedy_reference(params, prompt_b, 6)


def test_repetition_penalty_seen_mask_seeded_across_hit():
    """The skipped prefix must still count toward the repetition
    penalty: warm output with rep_pen equals the cold reference."""
    sp = SamplingParams(max_tokens=8, top_k=1, ignore_eos=True,
                        repetition_penalty=1.3)
    prompt = [(i * 7) % 250 + 3 for i in range(40)]
    eng, params = _build()
    with eng:
        cold = eng.submit(prompt, sp)
        cold.text()
        warm = eng.submit(prompt, sp)
        warm.text()
    assert eng.stats["prefix_cache_hit_tokens"] == 32
    assert warm.token_ids == cold.token_ids


def test_eviction_under_pool_pressure_and_page_conservation():
    """Distinct prompts churn through a pool too small to keep every
    retired prefix warm: admission evicts refcount-0 chains instead of
    backpressuring forever, every request completes, and no page is
    leaked or double-freed."""
    # extent = 32 + 16 -> 3 pages/request; 6-page pool holds at most two
    # retired 2-block prefixes, so the 4 distinct prompts force eviction
    eng, _ = _build(pool_tokens=96, max_in=32)
    sp = SamplingParams(max_tokens=4, top_k=1, ignore_eos=True)
    with eng:
        for r in range(4):
            s = eng.submit([(r * 31 + i) % 250 + 3 for i in range(32)], sp)
            s.text()
            assert s.finish_reason == "length" and len(s.token_ids) == 4
    stats = eng.stats
    assert stats["prefix_cache_evicted_pages"] > 0
    assert _pages_conserved(eng)


def test_warm_pages_reused_not_leaked_across_many_turns():
    """A growing multi-turn conversation keeps hitting: each turn's
    prompt extends the last, so hit tokens grow with the history."""
    eng, _ = _build()
    history = [(i * 3) % 250 + 3 for i in range(32)]
    hits = []
    with eng:
        for _turn in range(3):
            s = eng.submit(history, SP)
            s.text()
            hits.append(eng.stats["prefix_cache_hit_tokens"])
            history = history + s.token_ids \
                + [(len(history) * 7 + j) % 250 + 3 for j in range(10)]
    assert hits[0] == 0 and hits[1] > 0 and hits[2] > hits[1]
    assert _pages_conserved(eng)


def test_prefix_cache_disabled_by_config():
    eng, _ = _build(prefix=False)
    prompt = [(i * 7) % 250 + 3 for i in range(40)]
    with eng:
        a = eng.submit(prompt, SP)
        a.text()
        b = eng.submit(prompt, SP)
        b.text()
    assert "prefix_cache_hit_tokens" not in eng.stats
    assert a.token_ids == b.token_ids
    assert sorted(eng._free_pages) == list(range(1, eng._n_pages))


def test_int8_kv_prefix_hit_serves():
    """Structural: hits over a quantized pool admit and complete (the
    reused prefix reads back dequantized, so only the structure — not
    the bit trajectory — is pinned; same caveat as chunked int8)."""
    eng, _ = _build(kv_quant="int8")
    prompt = [(i * 9) % 250 + 3 for i in range(40)]
    with eng:
        a = eng.submit(prompt, SP)
        a.text()
        b = eng.submit(prompt, SP)
        b.text()
    assert eng.stats["prefix_cache_hit_tokens"] == 32
    assert b.finish_reason == "length" and len(b.token_ids) == 6
    assert a.token_ids[:3] == b.token_ids[:3]


def test_reset_clears_cache_and_serves_again():
    eng, _ = _build()
    prompt = [(i * 7) % 250 + 3 for i in range(40)]
    eng.start()
    eng.submit(prompt, SP).text()
    assert eng._prefix_cache.cached_pages > 0
    eng.reset()
    assert eng._prefix_cache.cached_pages == 0
    eng.start()
    s = eng.submit(prompt, SP)
    s.text()
    assert eng.stats["prefix_cache_hit_tokens"] == 0  # fresh cache
    eng.stop()
