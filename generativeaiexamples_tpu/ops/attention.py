"""Attention: GQA with absolute-position causal masking.

Replaces the reference's TRT GPT-attention plugin (reference:
conversion_scripts/llama/build.py:624-628 ``set_gpt_attention_plugin`` with
paged KV + remove-input-padding). Paged-KV decode attention lives in
``models/llama.py:apply_decode_paged`` (page gather + this kernel); XLA
fuses the masking/softmax chain here into the attention einsums.

Layout conventions (chosen for TPU tiling — head_dim last, 128-aligned):
  q:        (B, S, H,  hd)
  k, v:     (B, T, KV, hd)      T = key length (cache capacity)
  output:   (B, S, H,  hd)
GQA: H = KV * G. We reshape q to (B, S, KV, G, hd) and batch the KV heads —
the XLA analogue of the reference's KV-head duplication trick
(reference: conversion_scripts/llama/weight.py:150-157 ``dup_kv_weight``),
but without materializing duplicated KV.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # large-negative instead of -inf: avoids NaN from 0*inf


def gqa_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                  q_positions: jax.Array, kv_valid_len: jax.Array | None = None,
                  *, causal: bool = True) -> jax.Array:
    """Grouped-query attention over an absolute-position KV buffer.

    q_positions: (B, S) int32 — absolute position of each query token.
    kv_valid_len: (B,) int32 — number of valid keys per row (rest is padding
        in a fixed-capacity cache). None = all T keys valid.
    causal: query at position p attends keys at cache indices <= p. The KV
        buffer is indexed by absolute position (index i holds the token at
        position i), which is what the slotted cache guarantees.
    """
    B, S, H, hd = q.shape
    _, T, KV, _ = k.shape
    G = H // KV
    scale = 1.0 / (hd ** 0.5)

    qf = q.astype(jnp.float32).reshape(B, S, KV, G, hd)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)

    # scores: (B, KV, G, S, T)
    scores = jnp.einsum("bskgh,btkh->bkgst", qf, kf) * scale

    key_idx = jnp.arange(T, dtype=jnp.int32)
    mask = jnp.ones((B, S, T), dtype=bool)
    if causal:
        mask = key_idx[None, None, :] <= q_positions[:, :, None]
    if kv_valid_len is not None:
        mask = mask & (key_idx[None, None, :] < kv_valid_len[:, None, None])
    scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgst,btkh->bskgh", probs, vf)
    return out.reshape(B, S, H, hd).astype(q.dtype)
