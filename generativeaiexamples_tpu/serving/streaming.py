"""Bridging the engine's thread-world streams into asyncio responses."""

from __future__ import annotations

import asyncio
import contextvars
from typing import AsyncIterator, Iterator

_SENTINEL = object()


async def iterate_in_thread(it: Iterator[str],
                            on_cancel=None) -> AsyncIterator[str]:
    """Drive a blocking iterator on the default executor, yielding into the
    event loop with no polling: the producer thread hands each item to an
    asyncio.Queue via ``call_soon_threadsafe``. The producer never blocks
    on a dead consumer (the queue is unbounded; a cancelled consumer flips
    ``done`` and the producer drains out on its next item).

    ``on_cancel`` fires when the consumer abandons the iterator before it
    is exhausted (e.g. HTTP client disconnect) — pass the engine stream's
    ``cancel`` so abandoned requests release their decode slot instead of
    generating to max_tokens (ADVICE.md r1).
    """
    loop = asyncio.get_running_loop()
    q: "asyncio.Queue" = asyncio.Queue()
    done = False
    exhausted = False

    def _put(item) -> None:
        try:
            loop.call_soon_threadsafe(q.put_nowait, item)
        except RuntimeError:
            pass  # loop already closed — consumer is long gone

    def produce() -> None:
        try:
            for chunk in it:
                if done:
                    break
                _put(chunk)
        except BaseException as exc:  # noqa: BLE001 — surface in consumer
            _put(exc)
        finally:
            # Deterministically close generator chains so abandoned
            # requests propagate GeneratorExit down to the engine stream
            # (EngineLLM cancels its request from its finally).
            close = getattr(it, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # noqa: BLE001 — best-effort cleanup
                    pass
            _put(_SENTINEL)

    # Run the producer under the caller's contextvars: executor threads
    # don't inherit them, which would orphan the chain's OTel child spans
    # (retrieve/embed/llm) from the request's server span.
    ctx = contextvars.copy_context()
    producer = loop.run_in_executor(None, lambda: ctx.run(produce))
    try:
        while True:
            item = await q.get()
            if item is _SENTINEL:
                exhausted = True
                break
            if isinstance(item, BaseException):
                exhausted = True
                raise item
            yield item
    finally:
        done = True
        if not exhausted and on_cancel is not None:
            on_cancel()
        await producer
