"""LLM-as-judge Likert scoring of RAG answers.

Script form of the reference's human-like evaluation notebook
(reference: tools/evaluation/04_Human_Like_RAG_Evaluation-AIP.ipynb): a
few-shot judge prompt rates the assistant answer 1-5 against the
ground-truth context + answer, the ``Rating:``/``Explanation:`` fields are
regex-parsed with a retry loop, 0-ratings are clamped to 1, and the suite
reports the mean plus a 1-5 histogram (the notebook's matplotlib
histogram, as data).
"""

from __future__ import annotations

import re
from typing import Optional, Sequence

JUDGE_SYSTEM = (
    "You are an impartial judge evaluating the quality of an AI "
    "assistant's answer to a user question, given a reference context and "
    "a reference answer. Rate helpfulness, relevance, accuracy, and "
    "conciseness on a scale of 1 to 5. Respond in the exact format: "
    '"Rating": <1-5>, "Explanation": "<one sentence>".'
)

JUDGE_EXAMPLE = (
    "Example:\n"
    "[Question]\n"
    "What is the peak HBM bandwidth of the chip?\n"
    "[The Start of the Reference Context]\n"
    "The accelerator pairs a 128x128 systolic array with 16 GB of HBM "
    "delivering 819 GB/s of memory bandwidth.\n"
    "[The End of the Reference Context]\n"
    "[The Start of the Reference Answer]\n"
    "The chip's HBM provides 819 GB/s of peak bandwidth.\n"
    "[The End of the Reference Answer]\n"
    "[The Start of the Assistant's Answer]\n"
    "819 GB/s.\n"
    "[The End of the Assistant's Answer]\n"
    '"Rating": 5, "Explanation": "Accurate and concise; matches the '
    'reference answer exactly."\n'
)

JUDGE_PROMPT = (
    "{system}\n\n{example}\n"
    "Now evaluate the following.\n"
    "[Question]\n{question}\n"
    "[The Start of the Reference Context]\n{gt_context}\n"
    "[The End of the Reference Context]\n"
    "[The Start of the Reference Answer]\n{gt_answer}\n"
    "[The End of the Reference Answer]\n"
    "[The Start of the Assistant's Answer]\n{answer}\n"
    "[The End of the Assistant's Answer]\n"
)

_RATING = re.compile(r"Rating\"?\s*[:=]\s*\"?(\d+)", re.IGNORECASE)
_EXPLANATION = re.compile(r"Explanation\"?\s*[:=]\s*\"?(.+)", re.IGNORECASE)


def parse_rating(text: str) -> tuple[Optional[int], str]:
    m = _RATING.search(text)
    rating = int(m.group(1)) if m else None
    if rating is not None:
        # the notebook clamps stray 0s to 1; also clamp >5 hallucinations
        rating = min(5, max(1, rating))
    em = _EXPLANATION.search(text)
    explanation = em.group(1).strip().strip('"') if em else text.strip()
    return rating, explanation


def judge_answer(llm, question: str, gt_context: str, gt_answer: str,
                 answer: str, max_retries: int = 1,
                 ) -> tuple[Optional[int], str]:
    """Rate one answer 1-5; (None, raw_text) when no rating parsed after
    retries (reference notebook appends None and drops it from the mean)."""
    prompt = JUDGE_PROMPT.format(system=JUDGE_SYSTEM, example=JUDGE_EXAMPLE,
                                 question=question, gt_context=gt_context,
                                 gt_answer=gt_answer, answer=answer)
    explanation = ""
    for _ in range(1 + max_retries):
        text = llm.complete(prompt, max_tokens=200, temperature=0.1, top_k=4)
        rating, explanation = parse_rating(text)
        if rating is not None:
            return rating, explanation
    return None, explanation


def summarize_ratings(ratings: Sequence[Optional[int]]) -> dict:
    """Mean + histogram over parsed ratings (unparsed counted separately)."""
    parsed = [r for r in ratings if r is not None]
    hist = {str(i): sum(1 for r in parsed if r == i) for i in range(1, 6)}
    return {
        "mean_rating": (round(sum(parsed) / len(parsed), 2)
                        if parsed else None),
        "histogram": hist,
        "rated": len(parsed),
        "unparsed": len(ratings) - len(parsed),
    }
