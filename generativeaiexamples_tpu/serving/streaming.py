"""Bridging the engine's thread-world streams into asyncio responses."""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Iterator

_SENTINEL = object()


async def iterate_in_thread(it: Iterator[str]) -> AsyncIterator[str]:
    """Drive a blocking iterator on the default executor, yielding into the
    event loop with no polling: the producer thread hands each item to an
    asyncio.Queue via ``call_soon_threadsafe``. The producer never blocks
    on a dead consumer (the queue is unbounded; a cancelled consumer flips
    ``done`` and the producer drains out on its next item).
    """
    loop = asyncio.get_running_loop()
    q: "asyncio.Queue" = asyncio.Queue()
    done = False

    def _put(item) -> None:
        try:
            loop.call_soon_threadsafe(q.put_nowait, item)
        except RuntimeError:
            pass  # loop already closed — consumer is long gone

    def produce() -> None:
        try:
            for chunk in it:
                if done:
                    break
                _put(chunk)
        except BaseException as exc:  # noqa: BLE001 — surface in consumer
            _put(exc)
        finally:
            _put(_SENTINEL)

    producer = loop.run_in_executor(None, produce)
    try:
        while True:
            item = await q.get()
            if item is _SENTINEL:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        done = True
        await producer
