"""OpenTelemetry tracing spine, gated by ``ENABLE_TRACING``.

Parity with the reference's tracing modules:
- chain-server side extracts W3C traceparent from incoming request headers
  and wraps handlers in spans (reference: common/tracing.py:51-69);
- client side injects the current context into outgoing headers
  (reference: frontend/frontend/tracing.py:47-63).

When tracing is disabled (the default) every helper degrades to a no-op —
zero overhead, no SDK initialization, same as the reference's
``if not enabled`` fallthrough wrappers.

Enablement is evaluated PER CALL, not frozen at import: ``enabled()``
reads the env each time unless ``set_enabled()`` installed an override —
so config-file-driven ``tracing.enabled`` and tests toggling tracing
work without a module reimport, and ``enabled()`` / ``inject_context`` /
``event_span`` / ``instrumented`` all agree on the same check.
"""

from __future__ import annotations

import functools
import os
import time
from contextlib import contextmanager
from typing import Any, Optional

from . import metrics as _metrics

_enabled_override: Optional[bool] = None
_tracer = None


def enabled() -> bool:
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get("ENABLE_TRACING", "").lower() in ("1", "true",
                                                            "yes")


def set_enabled(value: Optional[bool]) -> None:
    """Force tracing on/off at runtime (config-file wiring, tests);
    ``None`` restores the ``ENABLE_TRACING`` env check."""
    global _enabled_override
    _enabled_override = value


def _get_tracer():
    """Lazy tracer init (service name 'chain-server' like the reference,
    common/tracing.py:32-48; OTLP endpoint from the standard env var).
    Returns None whenever tracing is off — a tracer initialized by an
    earlier enablement does not leak spans after set_enabled(False)."""
    global _tracer
    if not enabled():
        return None
    if _tracer is None:
        from opentelemetry import trace
        try:
            from opentelemetry.sdk.resources import Resource
            from opentelemetry.sdk.trace import TracerProvider
            from opentelemetry.sdk.trace.export import (BatchSpanProcessor,
                                                        ConsoleSpanExporter)

            service = os.environ.get("OTEL_SERVICE_NAME", "chain-server")
            provider = TracerProvider(
                resource=Resource.create({"service.name": service}))
            endpoint = os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT")
            if endpoint:
                try:
                    from opentelemetry.exporter.otlp.proto.grpc \
                        .trace_exporter import OTLPSpanExporter
                    provider.add_span_processor(BatchSpanProcessor(
                        OTLPSpanExporter(endpoint=endpoint)))
                except ImportError:
                    provider.add_span_processor(
                        BatchSpanProcessor(ConsoleSpanExporter()))
            trace.set_tracer_provider(provider)
        except ImportError:
            # api-only install: the global provider yields non-recording
            # spans — tracing stays wired but exports nothing.
            pass
        _tracer = trace.get_tracer("generativeaiexamples_tpu")
    return _tracer


@contextmanager
def server_span(name: str, headers: Optional[dict] = None,
                attributes: Optional[dict] = None):
    """Span with remote parent extracted from W3C headers
    (reference: common/tracing.py:56-58)."""
    tracer = _get_tracer()
    if tracer is None:
        yield None
        return
    from opentelemetry import trace
    from opentelemetry.propagate import extract
    ctx = extract(dict(headers or {}))
    with tracer.start_as_current_span(
            name, context=ctx, kind=trace.SpanKind.SERVER,
            attributes=attributes or {}) as span:
        yield span


def inject_context(headers: Optional[dict] = None) -> dict:
    """Inject current trace context into outgoing headers
    (reference: frontend/tracing.py:47-63)."""
    headers = dict(headers or {})
    if enabled():
        from opentelemetry.propagate import inject
        inject(headers)
    return headers


def instrumented(name: str):
    """Decorator for aiohttp handlers: wraps in a server span carrying the
    request's W3C context (reference: common/tracing.py:51-69
    ``instrumentation_wrapper``). No-op (identity passthrough of the
    handler's own behavior) when tracing is off."""
    def deco(handler):
        @functools.wraps(handler)
        async def wrapper(request, *args: Any, **kwargs: Any):
            if not enabled():
                return await handler(request, *args, **kwargs)
            with server_span(name, headers=request.headers,
                             attributes={"http.route": str(request.rel_url)}):
                return await handler(request, *args, **kwargs)
        return wrapper
    return deco


# Optional in-process stage-timing hook: callable(stage_name, seconds).
# Installed by diagnostics (set_stage_collector) for ad-hoc first-wins
# capture; record_stage additionally ALWAYS feeds the current request's
# flight-recorder timeline (obs/flight.py) and the labeled
# engine_stage_seconds histogram (obs/metrics.py observe_stage), so the
# per-stage breakdown exists in production scrapes and /debug/requests
# without any collector installed.
_stage_collector: Optional[Any] = None


def set_stage_collector(cb: Optional[Any]) -> None:
    """Install (or clear, with None) the process-local stage-timing hook."""
    global _stage_collector
    _stage_collector = cb


def record_stage(name: str, seconds: float) -> None:
    """Report one stage duration: to the installed collector (if any),
    to the bound request timeline, and to the stage histogram."""
    cb = _stage_collector
    if cb is not None:
        cb(name, seconds)
    from .flight import record_current_stage
    record_current_stage(name, seconds)
    _metrics.observe_stage(name, seconds)


@contextmanager
def event_span(kind: str, **attributes: Any):
    """Child span for pipeline events — the first-party replacement for the
    reference's LlamaIndex callback→OTel bridge
    (reference: tools/observability/llamaindex/opentelemetry_callback.py:
    84-197 maps QUERY/RETRIEVE/EMBEDDING/SYNTHESIZE/LLM events to spans).
    Chains call this directly around retrieve/embed/generate stages.
    The wall time is always reported through record_stage — stage
    histograms and flight timelines see every span site even with
    tracing off."""
    t0 = time.monotonic()
    try:
        tracer = _get_tracer()
        if tracer is None:
            yield None
            return
        clean = {k: v for k, v in attributes.items()
                 if isinstance(v, (str, int, float, bool))}
        with tracer.start_as_current_span(kind, attributes=clean) as span:
            yield span
    finally:
        record_stage(kind, time.monotonic() - t0)
