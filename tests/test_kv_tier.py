"""Tiered KV store tests (engine/kv_tier.py + engine integration).

Host-side unit coverage (store LRU/capacity, blob wire format, bounded
transfer fetch) plus engine-level serving tests on the CPU backend:
evict→offload→restore round trips must be token-identical to cold
recompute at page boundaries k·page±1 (including the COW-demoted tail
of a full-cover match), the restore-vs-recompute pricing must actually
refuse expensive restores, chaos plans must degrade to recompute /
cold placement (never an error frame), suspend/resume must round-trip
across engines, cross-replica transfer must move real pages over HTTP,
and KV_HOST_POOL_TOKENS=0 must preserve the untiered engine."""

import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from generativeaiexamples_tpu.engine import (Engine, EngineConfig,
                                             SamplingParams)
from generativeaiexamples_tpu.engine import kv_tier
from generativeaiexamples_tpu.engine.kv_tier import (BlockRecord,
                                                     HostPageStore,
                                                     fetch_blocks,
                                                     from_blob, to_blob)
from generativeaiexamples_tpu.engine.prefix_cache import hash_blocks
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import LlamaConfig
from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer
from generativeaiexamples_tpu.utils import faults

PAGE = 16

CFG = LlamaConfig(vocab_size=259 + 5, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  max_position_embeddings=256)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.key(31), dtype=jnp.float32)


@pytest.fixture(autouse=True)
def _no_env_tier(monkeypatch):
    # The engine reads KV_HOST_POOL_TOKENS at build; tests control the
    # tier via EngineConfig only.
    monkeypatch.delenv("KV_HOST_POOL_TOKENS", raising=False)
    yield
    faults.clear()


def _build(params, host_tokens, pool_tokens=96, max_in=64, max_out=16):
    cfg = EngineConfig(max_slots=2, max_input_length=max_in,
                       max_output_length=max_out,
                       prefill_buckets=(32, 64), page_size=PAGE,
                       dtype="float32", kv_pool_tokens=pool_tokens,
                       steps_per_round=4,
                       kv_host_pool_tokens=host_tokens)
    return Engine(params, CFG, ByteTokenizer(), cfg)


def _greedy_reference(params, prompt_ids, n_steps):
    ids = list(prompt_ids)
    for _ in range(n_steps):
        tokens = jnp.asarray(np.asarray(ids, np.int32)[None, :])
        pos = jnp.arange(len(ids), dtype=jnp.int32)[None, :]
        logits, _ = llama.apply(params, CFG, tokens, pos)
        ids.append(int(jnp.argmax(logits[0, -1])))
    return ids[len(prompt_ids):]


SP = SamplingParams(max_tokens=4, top_k=1, ignore_eos=True)


def _prompt(seed, n):
    return [(seed * 31 + i * 7) % 250 + 3 for i in range(n)]


def _wait_for_offload(eng, min_pages=1, timeout=5.0):
    """Offload materialization rides the harvest worker — wait for it."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if eng.stats["kv_tier_offload_pages"] >= min_pages:
            return
        time.sleep(0.02)
    raise AssertionError(
        f"offload never materialized: {eng.stats['kv_tier_offload_pages']}")


def _churn(eng, seeds, sp=SP, n=32):
    """Serve distinct prompts to push earlier prefixes out of the pool
    (96-token pool = 6 pages; each request holds 3)."""
    for s in seeds:
        stream = eng.submit(_prompt(s, n), sp)
        stream.text()
        assert stream.finish_reason == "length"


# --------------------------------------------------------------- unit level

def test_host_store_lru_capacity_and_chain_match():
    # each record: one (2,2) float32 leaf = 16 bytes; cap = 2 records
    store = HostPageStore(capacity_bytes=32)
    recs = [BlockRecord(bytes([i]) * 16, None,
                        {"k": np.full((2, 2), i, np.float32)})
            for i in range(3)]
    assert store.put(recs[0]) and store.put(recs[1])
    assert store.nbytes == 32
    assert store.get(recs[0].hash) is not None   # refresh 0's recency
    store.put(recs[2])                            # evicts 1 (LRU)
    assert store.has(recs[0].hash) and store.has(recs[2].hash)
    assert not store.has(recs[1].hash)
    assert store.offload_evictions == 1
    assert store.pages == 2 and store.nbytes == 32
    # chain match stops at the first gap
    assert store.match_chain([recs[0].hash, recs[2].hash]) == 2
    assert store.match_chain([recs[1].hash, recs[0].hash]) == 0
    assert store.match_chain([recs[0].hash, recs[1].hash,
                              recs[2].hash]) == 1
    # pop keeps the byte ledger honest
    assert store.pop(recs[0].hash) is not None
    assert store.nbytes == 16
    # the capacity is BYTES: a single record over the whole budget is
    # refused outright (an inflated import cannot evict everything),
    # and a disabled store takes nothing
    huge = BlockRecord(b"h" * 16, None,
                       {"k": np.zeros((100,), np.float32)})
    assert not store.put(huge)
    assert not HostPageStore(0).put(recs[0])


def test_blob_round_trip_and_truncation():
    recs = [
        BlockRecord(b"a" * 16, None,
                    {"k": np.arange(24, dtype=np.float32).reshape(2, 3, 4),
                     "v": np.ones((2, 3, 4), np.float32)}),
        BlockRecord(b"b" * 16, b"a" * 16,
                    {"k": np.zeros((2, 3, 4), np.float32),
                     "v": np.full((2, 3, 4), 7, np.float32)}),
    ]
    meta = {"page_size": PAGE, "kv_quant": "", "dtype": "float32"}
    blob = to_blob(recs, meta)
    meta2, recs2 = from_blob(blob)
    assert meta2["page_size"] == PAGE
    assert [r.hash for r in recs2] == [r.hash for r in recs]
    assert recs2[1].parent == b"a" * 16
    for a, b in zip(recs, recs2):
        for name in a.arrays:
            np.testing.assert_array_equal(a.arrays[name], b.arrays[name])
    with pytest.raises(ValueError):
        from_blob(blob[:-10])       # truncated payload fails loudly
    with pytest.raises(ValueError):
        from_blob(b"junk" + blob)   # bad magic


def test_fetch_blocks_hang_is_bounded():
    faults.set_plan("kv.transfer=hang")
    t0 = time.monotonic()
    out = fetch_blocks("http://127.0.0.1:1", [b"x" * 16], timeout_s=0.4)
    assert out is None
    assert time.monotonic() - t0 < 3.0   # bounded by timeout, not HANG_MAX
    faults.clear()
    # connect-refused donor: also None, no raise
    assert fetch_blocks("http://127.0.0.1:1", [b"x" * 16],
                        timeout_s=0.5) is None


# ------------------------------------------------------------- engine level

@pytest.mark.parametrize("n_tokens", [PAGE - 1, 2 * PAGE - 1, 2 * PAGE,
                                      2 * PAGE + 1, 3 * PAGE + 1])
def test_offload_restore_parity_at_page_boundaries(params, n_tokens):
    """evict→offload→restore must be token-identical to cold recompute
    at k·page±1, including the COW-demoted tail of a full-cover match
    (2*PAGE: both blocks offloaded, only the first restorable)."""
    eng = _build(params, host_tokens=4096)
    target = _prompt(1, n_tokens)
    with eng:
        cold = eng.submit(target, SP)
        cold.text()
        _churn(eng, seeds=(50, 51, 52))    # push target out of the pool
        if n_tokens >= PAGE:               # sub-page prompts cache nothing
            _wait_for_offload(eng)
        warm = eng.submit(target, SP)
        warm.text()
    stats = eng.stats
    ref = _greedy_reference(params, target, 4)
    assert cold.token_ids == ref
    assert warm.token_ids == ref
    if n_tokens >= PAGE:
        # COW cap: a full-cover chain restores one block short
        expect_pages = (n_tokens - 1) // PAGE
        assert stats["kv_tier_restore_pages"] >= min(1, expect_pages)
        if expect_pages:
            assert stats["kv_tier_restore_hits"] >= 1
            assert stats["kv_restore_hit_rate"] > 0
    # page conservation: free + cached == pool
    cached = eng._prefix_cache.cached_pages
    assert len(eng._free_pages) + cached == eng._n_pages - 1


def test_pricing_skips_expensive_restore(params, monkeypatch, tmp_path):
    """A cost model pricing H2D above recompute must deliberately
    re-prefill — and say so via kv_restore_skipped_cost — with
    token-identical output."""
    import json
    prof = tmp_path / "PROFILE_skip.json"
    prof.write_text(json.dumps({
        "full_ms_per_step": 2.0, "slots": 8,
        "prefill_ms_per_token": 0.0001, "h2d_ms_per_page": 1e9}))
    monkeypatch.setenv("SCHED_PROFILE_JSON", str(prof))
    monkeypatch.setenv("SCHED_ONLINE_CALIB", "0")
    eng = _build(params, host_tokens=4096)
    target = _prompt(2, 2 * PAGE + 1)
    with eng:
        cold = eng.submit(target, SP)
        cold.text()
        _churn(eng, seeds=(60, 61, 62))
        _wait_for_offload(eng)
        warm = eng.submit(target, SP)
        warm.text()
    stats = eng.stats
    assert stats["kv_restore_skipped_cost"] >= 1
    assert stats["kv_tier_restore_pages"] == 0
    assert warm.token_ids == cold.token_ids \
        == _greedy_reference(params, target, 4)


def test_chaos_restore_fail_falls_back_to_recompute(params):
    """kv.restore=fail: the admission recomputes the prefix — correct
    tokens, a clean `length` finish, no error surface."""
    eng = _build(params, host_tokens=4096)
    target = _prompt(3, 2 * PAGE + 1)
    with eng:
        cold = eng.submit(target, SP)
        cold.text()
        _churn(eng, seeds=(70, 71, 72))
        _wait_for_offload(eng)
        faults.set_plan("kv.restore=fail")
        try:
            warm = eng.submit(target, SP)
            text = warm.text()      # no EngineError raised
            fired = faults.fired("kv.restore")
        finally:
            faults.clear()
    assert fired >= 1
    assert warm.finish_reason == "length"
    assert "[error]" not in text
    assert warm.token_ids == cold.token_ids
    assert eng.stats["kv_tier_restore_pages"] == 0


def test_chaos_offload_fail_drops_pages_untiered(params):
    eng = _build(params, host_tokens=4096)
    faults.set_plan("kv.offload=fail")
    try:
        with eng:
            _churn(eng, seeds=(80, 81, 82, 83))
    finally:
        faults.clear()
    stats = eng.stats
    assert stats["prefix_cache_evicted_pages"] > 0   # eviction proceeded
    assert stats["kv_tier_offload_pages"] == 0       # nothing offloaded


def test_chaos_transfer_hang_places_cold(params):
    """kv.transfer=hang on the requester: submit() pays the bounded
    fetch timeout, then serves a normal cold prefill."""
    eng = _build(params, host_tokens=4096)
    eng._kv_tier.transfer_timeout_s = 0.3
    target = _prompt(4, 2 * PAGE)
    faults.set_plan("kv.transfer=hang")
    token = kv_tier.bind_transfer_source("http://127.0.0.1:1")
    try:
        with eng:
            # bound SUBMIT, where the fetch lives — text() would fold
            # in compile time and flake under parallel test load
            t0 = time.monotonic()
            stream = eng.submit(target, SP)
            submit_s = time.monotonic() - t0
            stream.text()
            assert submit_s < 5.0, submit_s
    finally:
        kv_tier.unbind_transfer_source(token)
        faults.clear()
    assert stream.finish_reason == "length"
    assert stream.token_ids == _greedy_reference(params, target, 4)
    assert eng.stats["kv_tier_transfer_pages"] == 0


def test_export_handoff_keeps_pages_resident(params):
    """Disaggregation donor side (docs/disaggregation.md): unlike
    suspend, export_handoff leaves the pages RESIDENT — the donor keeps
    serving pull-side /control/kv_pages fallbacks for the same prefix —
    and the blob round-trips the full chain."""
    eng = _build(params, host_tokens=4096)
    target = _prompt(12, 3 * PAGE)
    with eng:
        cold = eng.submit(target, SP)
        cold.text()
        cached_before = eng._prefix_cache.cached_pages
        out = eng.export_handoff(target)
        assert out is not None
        blob, n = out
        assert n == 3
        # pages stayed put — nothing was demoted or dropped
        assert eng._prefix_cache.cached_pages == cached_before
        meta, recs = from_blob(blob)
        assert [r.hash for r in recs] == hash_blocks(target, PAGE)
        assert meta["page_size"] == PAGE
        # a chain this engine never served exports nothing
        assert eng.export_handoff(_prompt(99, 2 * PAGE)) is None
    assert eng.stats["kv_tier_export_pages"] == 3
    untiered = _build(params, host_tokens=0)
    from generativeaiexamples_tpu.utils.errors import EngineError
    with pytest.raises(EngineError, match="disabled"):
        untiered.export_handoff(target)


def test_push_blob_hang_and_dead_target_bounded():
    """The handoff push (donor → decode /control/kv_resume) must be
    bounded like the pull: a hung transfer or a dead receiver answers
    False within timeout_s — the donor then reports pushed=false and
    the router falls back to recompute."""
    faults.set_plan("kv.transfer=hang")
    t0 = time.monotonic()
    assert kv_tier.push_blob("http://127.0.0.1:1", b"x",
                             timeout_s=0.4) is False
    assert time.monotonic() - t0 < 3.0
    assert faults.fired("kv.transfer") >= 1
    faults.clear()
    # connect-refused receiver: also False, no raise
    assert kv_tier.push_blob("http://127.0.0.1:1", b"x",
                             timeout_s=0.5) is False


def test_suspend_resume_round_trip_across_engines(params):
    """Suspend on engine A, resume on engine B (same geometry): B's
    next turn restores without recompute, token-identical."""
    a = _build(params, host_tokens=4096)
    history = _prompt(5, 3 * PAGE + 5)
    with a:
        cold = a.submit(history, SP)
        cold.text()
        cached_before = a._prefix_cache.cached_pages
        blob = a.suspend_session(history)
        assert blob is not None
        # demotion actually freed HBM pages
        assert a._prefix_cache.cached_pages < cached_before
        assert a.stats["kv_tier_suspended_blocks"] == 3
    b = _build(params, host_tokens=4096)
    with b:
        assert b.resume_session(blob) == 3
        warm = b.submit(history, SP)
        warm.text()
    stats = b.stats
    assert stats["kv_tier_resumed_blocks"] == 3
    assert stats["kv_tier_restore_pages"] == 3   # COW caps at 3 of 3 full
    assert warm.token_ids == cold.token_ids \
        == _greedy_reference(params, history, 4)


def test_reset_fails_pending_control_ops(params):
    """A control op queued against a generation reset() kills must fail
    its waiter immediately — never hang the 30 s timeout, never execute
    against the rebuilt state (a stale suspend would demote a fresh
    cache)."""
    import threading

    from generativeaiexamples_tpu.utils.errors import EngineError
    eng = _build(params, host_tokens=4096)
    box: dict = {}
    ev = threading.Event()
    ran = []
    eng._control.put((lambda: ran.append(1), box, ev))
    eng.reset()
    assert ev.is_set()
    assert isinstance(box.get("error"), EngineError)
    assert not ran                       # never executed
    assert eng._control.empty()          # fresh queue


def test_resume_rejects_geometry_mismatch(params):
    from generativeaiexamples_tpu.utils.errors import EngineError
    eng = _build(params, host_tokens=4096)
    history = _prompt(6, 2 * PAGE)
    with eng:
        eng.submit(history, SP).text()
        blob = eng.suspend_session(history)
    meta, recs = from_blob(blob)
    bad = to_blob(recs, dict(meta, page_size=999))
    with pytest.raises(EngineError, match="geometry"):
        eng.resume_session(bad)
    with pytest.raises(EngineError, match="blob"):
        eng.resume_session(b"not a blob at all")


def test_tier_disabled_preserves_untiered_behavior(params):
    """KV_HOST_POOL_TOKENS=0: no tier object, no offload/restore, the
    eviction path and tokens identical to the pre-tier engine."""
    eng = _build(params, host_tokens=0)
    assert eng._kv_tier is None
    target = _prompt(7, 2 * PAGE + 1)
    with eng:
        cold = eng.submit(target, SP)
        cold.text()
        _churn(eng, seeds=(90, 91, 92))
        warm = eng.submit(target, SP)   # re-prefills: pages were dropped
        warm.text()
    stats = eng.stats
    assert stats["prefix_cache_evicted_pages"] > 0
    for key in ("kv_tier_offload_pages", "kv_tier_restore_pages",
                "kv_tier_restore_hits", "kv_restore_skipped_cost",
                "kv_tier_transfer_pages", "kv_tier_host_pages"):
        assert stats[key] == 0, key
    assert warm.token_ids == cold.token_ids \
        == _greedy_reference(params, target, 4)
    from generativeaiexamples_tpu.utils.errors import EngineError
    with pytest.raises(EngineError, match="disabled"):
        eng.suspend_session(target)


def test_donor_allowlist(monkeypatch):
    monkeypatch.delenv("KV_TRANSFER_ALLOW", raising=False)
    assert kv_tier.donor_allowed("http://anything")      # default: trust
    monkeypatch.setenv("KV_TRANSFER_ALLOW",
                       "http://10.0.3.7, http://replica-2:8081")
    assert kv_tier.donor_allowed("http://10.0.3.7:8081")     # : boundary
    assert kv_tier.donor_allowed("http://10.0.3.7/x")        # / boundary
    assert kv_tier.donor_allowed("http://replica-2:8081")    # exact
    assert kv_tier.donor_allowed("http://replica-2:8081/a")
    assert not kv_tier.donor_allowed("http://attacker.example")
    # startswith alone is NOT a boundary: an attacker-controlled
    # hostname extending an allow entry must not pass
    assert not kv_tier.donor_allowed("http://10.0.3.71:8081")
    assert not kv_tier.donor_allowed(
        "http://replica-2.attacker.example")


def test_transfer_rejects_unrequested_blocks(params, monkeypatch):
    """A donor answer may only land blocks the requester ASKED for —
    anything else could poison unrelated cached prefixes through the
    shared host store."""
    eng = _build(params, host_tokens=4096)
    target = _prompt(11, 2 * PAGE)
    hashes = hash_blocks(target, PAGE)
    rogue = BlockRecord(b"R" * 16, None,
                        {"k": np.zeros((2, 2), np.float32)})
    good = BlockRecord(hashes[0], None,
                       {"k": np.zeros((2, 2), np.float32)})

    def fake_fetch(url, missing, **kw):
        return dict(eng._kv_tier.meta), [rogue, good]

    monkeypatch.setattr(kv_tier, "fetch_blocks", fake_fetch)
    token = kv_tier.bind_transfer_source("http://donor")
    try:
        req_like = type("R", (), {})()
        req_like.prompt_ids = target
        req_like.block_hashes = None
        req_like.stream = type("S", (), {"timeline": None})()
        eng._transfer_prefetch(req_like)
    finally:
        kv_tier.unbind_transfer_source(token)
    assert eng._kv_tier.store.has(hashes[0])
    assert not eng._kv_tier.store.has(b"R" * 16)
    assert eng.stats["kv_tier_transfer_pages"] == 1


def test_int8_kv_offload_restore_serves(params):
    """Structural: the tier round-trips a QUANTIZED pool's four leaves
    (int8 k/v + scale pools) — offloaded pages restore and serve. The
    reused prefix reads back dequantized, so only the structure — not
    the bit trajectory — is pinned (same caveat as warm int8 hits)."""
    cfg = EngineConfig(max_slots=2, max_input_length=64,
                       max_output_length=16, prefill_buckets=(32, 64),
                       page_size=PAGE, dtype="float32",
                       kv_pool_tokens=96, steps_per_round=4,
                       kv_quant="int8", kv_host_pool_tokens=4096)
    eng = Engine(params, CFG, ByteTokenizer(), cfg)
    target = _prompt(9, 2 * PAGE + 1)
    with eng:
        cold = eng.submit(target, SP)
        cold.text()
        _churn(eng, seeds=(95, 96, 97))
        _wait_for_offload(eng)
        warm = eng.submit(target, SP)
        warm.text()
    stats = eng.stats
    assert stats["kv_tier_restore_pages"] >= 1
    assert warm.finish_reason == "length" and len(warm.token_ids) == 4
    assert warm.token_ids[:2] == cold.token_ids[:2]


def test_cross_replica_transfer_end_to_end(params):
    """Donor replica A serves a conversation; replica B — hinted via
    the transfer contextvar, exactly what the chain server binds from
    X-KV-Transfer-From — fetches A's prefix pages over a REAL
    /control/kv_pages HTTP endpoint and restores them at admission,
    token-identical to recompute."""
    from types import SimpleNamespace

    import bench
    from generativeaiexamples_tpu.chains.server import create_app

    a = _build(params, host_tokens=4096)
    b = _build(params, host_tokens=4096)
    target = _prompt(8, 3 * PAGE)
    try:
        a.start()
        cold = a.submit(target, SP)
        cold.text()
        app = create_app(SimpleNamespace(
            llm=SimpleNamespace(engine=a)))
        (url,), stop = bench.serve_apps([app])
        try:
            token = kv_tier.bind_transfer_source(url)
            try:
                b.start()
                warm = b.submit(target, SP)
                warm.text()
            finally:
                kv_tier.unbind_transfer_source(token)
        finally:
            stop()
        stats_b = b.stats
        assert stats_b["kv_tier_transfer_pages"] == 3
        # COW: 2 of the 3 fetched blocks restore (tail recomputed)
        assert stats_b["kv_tier_restore_pages"] == 2
        assert warm.token_ids == cold.token_ids \
            == _greedy_reference(params, target, 4)
        # the donor's export also warmed its own host tier
        assert a.stats["kv_tier_host_pages"] == 3
    finally:
        a.stop()
        b.stop()


def test_transfer_donor_selection():
    """Router-side hint logic: a sibling whose sketch covers the prompt
    head strictly better than the chosen replica (and by >= min_blocks)
    is the donor; otherwise no hint."""
    from generativeaiexamples_tpu.router.table import ReplicaTable

    table = ReplicaTable()
    r0 = table.add("r0", "http://r0")
    r1 = table.add("r1", "http://r1")
    blocks = table.affinity_blocks("s" * 400)
    assert table.transfer_donor(blocks, chosen="r0") is None
    table.record_placement(r1, blocks)        # r1 knows the prefix
    assert table.transfer_donor(blocks, chosen="r0") == "http://r1"
    assert table.transfer_donor(blocks, chosen="r1") is None  # self
    # min_blocks gates small matches
    assert table.transfer_donor(blocks[:1], chosen="r0",
                                min_blocks=2) is None
    # unreachable donors are never named
    table.mark_unreachable("r1")
    assert table.transfer_donor(blocks, chosen="r0") is None
    # draining donors still serve pages
    table.update_health("r1", ok=True, ready=False,
                        body={"draining": True})
    assert table.transfer_donor(blocks, chosen="r0") == "http://r1"
    assert r0.name == "r0"
