"""ctypes loader for the native top-k kernels, compiled on demand.

First call compiles ``topk.cpp`` with g++ (OpenMP) into a cached shared
library next to this file; if no toolchain is available the callers fall
back to numpy transparently. This is the framework's own native-code answer
to the reference's FAISS / knowhere C++ search engines
(reference: common/utils.py:181-198).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "topk.cpp")
_LIB = os.path.join(_HERE, "libgaietopk.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_i64 = ctypes.c_int64
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _compile() -> bool:
    cmd = ["g++", "-O3", "-fopenmp", "-shared", "-fPIC", "-std=c++17",
           _SRC, "-o", _LIB]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError) as exc:
        logger.info("native topk unavailable (%s); using numpy fallback", exc)
        return False


def load() -> Optional[ctypes.CDLL]:
    """The library, compiling it on first use; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB) or (
                os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            if not _compile():
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            # Stale/foreign-arch binary (e.g. copied between hosts):
            # rebuild once before giving up.
            if not _compile():
                return None
            try:
                lib = ctypes.CDLL(_LIB)
            except OSError as exc:
                logger.info("native topk load failed: %s", exc)
                return None
        lib.gaie_brute_topk.argtypes = [
            _f32p, ctypes.c_void_p, ctypes.c_void_p, _i64, _i64,
            _f32p, _i64, _i64, ctypes.c_int, _i64p, _f32p]
        lib.gaie_ivf_search.argtypes = [
            _f32p, ctypes.c_void_p, ctypes.c_void_p, _i64,
            _f32p, _i64, _i64p, _i64p,
            _f32p, _i64, _i64, _i64, ctypes.c_int, _i64p, _f32p]
        lib.gaie_num_threads.restype = ctypes.c_int
        _lib = lib
        return _lib


def _opt(arr: Optional[np.ndarray]) -> Optional[ctypes.c_void_p]:
    if arr is None:
        return None
    return arr.ctypes.data_as(ctypes.c_void_p)


def brute_topk(base: np.ndarray, queries: np.ndarray, k: int, metric: int,
               base_sq: Optional[np.ndarray] = None,
               live: Optional[np.ndarray] = None,
               ) -> Optional[tuple[np.ndarray, np.ndarray]]:
    """(idx, score) each (Q, k), or None when the native lib is unavailable."""
    lib = load()
    if lib is None:
        return None
    nq, n = queries.shape[0], base.shape[0]
    idx = np.empty((nq, k), np.int64)
    score = np.empty((nq, k), np.float32)
    lib.gaie_brute_topk(base, _opt(base_sq), _opt(live), n, base.shape[1],
                        queries, nq, k, metric, idx, score)
    return idx, score


def ivf_search(base: np.ndarray, centroids: np.ndarray, offsets: np.ndarray,
               items: np.ndarray, queries: np.ndarray, k: int, nprobe: int,
               metric: int, base_sq: Optional[np.ndarray] = None,
               live: Optional[np.ndarray] = None,
               ) -> Optional[tuple[np.ndarray, np.ndarray]]:
    lib = load()
    if lib is None:
        return None
    nq = queries.shape[0]
    idx = np.empty((nq, k), np.int64)
    score = np.empty((nq, k), np.float32)
    lib.gaie_ivf_search(base, _opt(base_sq), _opt(live), base.shape[1],
                        centroids, centroids.shape[0], offsets, items,
                        queries, nq, k, nprobe, metric, idx, score)
    return idx, score
