"""Resume-continuation context: how a transcript-replay resume reaches
``Engine.submit`` without threading a parameter through every chain.

On a mid-stream replica loss the fleet router re-submits the original
request to a sibling with the generated-so-far transcript attached
(docs/robustness.md). The chain server tokenizes that transcript and
binds the replayed token ids here; the bound value rides the request's
copied context through ``iterate_in_thread`` into ``Engine.submit`` —
the same contextvar pattern as the flight timeline (``obs/flight.py``)
and the KV-transfer donor hint (``engine/kv_tier.py``).

``Engine.submit`` reads the block once and admits the request as
``prompt + replayed tokens``: the replayed prefix is PROMPT, so the
prefix cache / host-tier restore / donor transfer make it cheap, the
rep-penalty seen mask covers it exactly as prefix-cache admission
already does, and the detokenizer/stop-trap stream only NEW text. The
replay offset also pins the admission RNG key (``_admit``) so a resumed
request with the same seed draws the same continuation stream where the
sampler consumes per-request randomness.
"""

from __future__ import annotations

import contextvars
from typing import Optional

#: ``{"ids": [int, ...], "attempt": int}`` — replayed generated-so-far
#: token ids (NO BOS; they follow the prompt) and the resume attempt
#: ordinal (observability only).
_RESUME: contextvars.ContextVar[Optional[dict]] = contextvars.ContextVar(
    "engine_resume_block", default=None)


def bind_resume(block: dict) -> contextvars.Token:
    """Bind a resume block for the current context; returns the token
    for ``unbind_resume``. The caller (chains/server.py) binds before
    starting the chain generator and unbinds in its ``finally``."""
    return _RESUME.set(dict(block))


def unbind_resume(token: contextvars.Token) -> None:
    _RESUME.reset(token)


def current_resume() -> Optional[dict]:
    """The bound resume block, or None for an ordinary request."""
    return _RESUME.get()
