"""First-party IVF-Flat ANN store.

Parameter parity with the reference's Milvus GPU_IVF_FLAT defaults —
nlist=64, nprobe=16 (reference: common/utils.py:181-186,
common/configuration.py:38-47). K-means runs in numpy (nlist is small);
search scans the nprobe nearest clusters' postings via the native C++
kernel when available, numpy otherwise.
"""

from __future__ import annotations

import json
import os
from typing import Optional, Sequence

import numpy as np

from .store import SearchHit, VectorStore, _as_2d, score_matrix


def kmeans(data: np.ndarray, n_clusters: int, iters: int = 20,
           seed: int = 0) -> np.ndarray:
    """Lloyd's k-means; returns (n_clusters, D) centroids."""
    rng = np.random.default_rng(seed)
    n = data.shape[0]
    centroids = data[rng.choice(n, size=min(n_clusters, n), replace=False)]
    if centroids.shape[0] < n_clusters:  # fewer points than clusters
        extra = rng.standard_normal(
            (n_clusters - centroids.shape[0], data.shape[1])).astype(np.float32)
        centroids = np.concatenate([centroids, extra])
    for _ in range(iters):
        assign = assign_clusters(data, centroids)
        for c in range(n_clusters):
            members = data[assign == c]
            if len(members):
                centroids[c] = members.mean(axis=0)
    return centroids


def assign_clusters(data: np.ndarray, centroids: np.ndarray) -> np.ndarray:
    d2 = (np.einsum("nd,nd->n", data, data)[:, None]
          - 2.0 * data @ centroids.T
          + np.einsum("cd,cd->c", centroids, centroids)[None, :])
    return np.argmin(d2, axis=1).astype(np.int64)


class IVFFlatStore(VectorStore):
    def __init__(self, dim: int, metric: str = "ip", nlist: int = 64,
                 nprobe: int = 16, train_min: Optional[int] = None):
        if metric not in ("ip", "l2"):
            raise ValueError(f"metric must be ip|l2, got {metric!r}")
        self._dim = dim
        self.metric = metric
        self.nlist = nlist
        self.nprobe = nprobe
        # Below this corpus size search just brute-forces (and no train).
        self.train_min = train_min if train_min is not None else 4 * nlist
        self._rows: list[np.ndarray] = []
        self._live_list: list[bool] = []
        self._deleted = 0
        self._index: Optional[dict] = None  # centroids/offsets/items/base/...

    @property
    def dim(self) -> int:
        return self._dim

    def __len__(self) -> int:
        return len(self._rows) - self._deleted

    def add(self, embeddings: np.ndarray) -> list[int]:
        emb = _as_2d(embeddings)
        if emb.shape[1] != self._dim:
            raise ValueError(f"dim mismatch: store {self._dim}, got {emb.shape[1]}")
        start = len(self._rows)
        for row in emb:
            self._rows.append(np.ascontiguousarray(row, np.float32))
            self._live_list.append(True)
        self._index = None  # lazily rebuilt on next search
        return list(range(start, start + emb.shape[0]))

    def delete(self, ids: Sequence[int]) -> None:
        for i in ids:
            if 0 <= i < len(self._rows) and self._live_list[i]:
                self._live_list[i] = False
                self._deleted += 1
        if self._index is not None:
            self._index["live"] = np.asarray(self._live_list, np.uint8)

    # ------------------------------------------------------------- indexing

    def _build(self) -> dict:
        base = np.stack(self._rows) if self._rows else np.zeros(
            (0, self._dim), np.float32)
        live = np.asarray(self._live_list, np.uint8)
        idx: dict = {"base": base, "live": live,
                     "sq": np.einsum("nd,nd->n", base, base)}
        if base.shape[0] >= self.train_min:
            centroids = kmeans(base, self.nlist)
            assign = assign_clusters(base, centroids)
            order = np.argsort(assign, kind="stable").astype(np.int64)
            counts = np.bincount(assign, minlength=self.nlist)
            offsets = np.zeros(self.nlist + 1, np.int64)
            np.cumsum(counts, out=offsets[1:])
            idx.update(centroids=np.ascontiguousarray(centroids, np.float32),
                       offsets=offsets, items=order)
        return idx

    def search(self, queries: np.ndarray, k: int = 4) -> list[list[SearchHit]]:
        q = np.ascontiguousarray(_as_2d(queries), np.float32)
        if len(self) == 0:
            return [[] for _ in range(q.shape[0])]
        if self._index is None:
            self._index = self._build()
        ix = self._index
        k_eff = min(k, len(self))
        metric_code = 0 if self.metric == "ip" else 1
        any_dead = self._deleted > 0
        live = ix["live"] if any_dead else None
        if "centroids" in ix:
            from . import native
            out = native.ivf_search(ix["base"], ix["centroids"], ix["offsets"],
                                    ix["items"], q, k_eff, self.nprobe,
                                    metric_code,
                                    base_sq=ix["sq"], live=live)
            if out is None:
                out = self._numpy_ivf(ix, q, k_eff)
        else:
            from . import native
            out = native.brute_topk(ix["base"], q, k_eff, metric_code,
                                    base_sq=ix["sq"], live=live)
            if out is None:
                out = self._numpy_brute(ix, q, k_eff)
        idx_arr, score_arr = out
        return [
            [SearchHit(int(i), float(s)) for i, s in zip(ri, rs) if i >= 0]
            for ri, rs in zip(idx_arr, score_arr)
        ]

    def _numpy_brute(self, ix: dict, q: np.ndarray, k: int):
        scores = score_matrix(ix["base"], q, self.metric, base_sqnorm=ix["sq"])
        if self._deleted > 0:
            scores = np.where(ix["live"][None, :] == 1, scores, -np.inf)
        idx = np.argsort(-scores, axis=1)[:, :k]
        top = np.take_along_axis(scores, idx, axis=1)
        idx = np.where(np.isfinite(top), idx, -1)
        return idx.astype(np.int64), top.astype(np.float32)

    def _numpy_ivf(self, ix: dict, q: np.ndarray, k: int):
        nq = q.shape[0]
        idx = np.full((nq, k), -1, np.int64)
        score = np.full((nq, k), -np.inf, np.float32)
        cd2 = (np.einsum("cd,cd->c", ix["centroids"], ix["centroids"])[None, :]
               - 2.0 * q @ ix["centroids"].T)
        probe = np.argsort(cd2, axis=1)[:, :self.nprobe]
        for qi in range(nq):
            cand: list[np.ndarray] = []
            for c in probe[qi]:
                cand.append(ix["items"][ix["offsets"][c]:ix["offsets"][c + 1]])
            ids = np.concatenate(cand) if cand else np.zeros(0, np.int64)
            if self._deleted > 0:
                ids = ids[ix["live"][ids] == 1]
            if not len(ids):
                continue
            sub = score_matrix(ix["base"][ids], q[qi:qi + 1], self.metric,
                               base_sqnorm=ix["sq"][ids])[0]
            order = np.argsort(-sub)[:k]
            idx[qi, :len(order)] = ids[order]
            score[qi, :len(order)] = sub[order]
        return idx, score

    # ---------------------------------------------------------- persistence

    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        base = np.stack(self._rows) if self._rows else np.zeros(
            (0, self._dim), np.float32)
        np.savez_compressed(os.path.join(path, "vectors.npz"), data=base,
                            live=np.asarray(self._live_list, np.uint8))
        with open(os.path.join(path, "store.json"), "w") as f:
            json.dump({"kind": "ivfflat", "dim": self._dim,
                       "metric": self.metric, "nlist": self.nlist,
                       "nprobe": self.nprobe}, f)

    @classmethod
    def load(cls, path: str) -> "IVFFlatStore":
        with open(os.path.join(path, "store.json")) as f:
            meta = json.load(f)
        z = np.load(os.path.join(path, "vectors.npz"))
        store = cls(dim=meta["dim"], metric=meta["metric"],
                    nlist=meta["nlist"], nprobe=meta["nprobe"])
        for row, lv in zip(z["data"], z["live"]):
            store._rows.append(np.ascontiguousarray(row, np.float32))
            store._live_list.append(bool(lv))
        store._deleted = int(len(store._rows) - z["live"].sum())
        return store
