"""Model architecture configs and the named-model registry.

Covers the model families the reference serves: Llama-2 chat 7B/13B/70B and
CodeLlama (reference: model_server/model.py:76-87 ``ModelTypes``
LLAMA/CODE_LLAMA/GPTNEXT; docs/rag/support_matrix.md sizing), the
e5-large-v2 embedder (reference: common/configuration.py:95-121), and
Mixtral-8x7B for expert parallelism (reference uses it via cloud endpoints
only, examples/5_mins_rag_no_gpu/main.py:50 — here it is first-class).
"""

from __future__ import annotations

from dataclasses import dataclass, replace


@dataclass(frozen=True)
class LlamaConfig:
    """Decoder-only transformer (Llama-2 family geometry)."""
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: int = 128
    max_position_embeddings: int = 4096
    rope_theta: float = 10000.0
    rope_scaling_factor: float = 1.0
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    # MoE (Mixtral): 0 experts = dense MLP.
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # "sparse" = top-k capacity routing (parallel/moe.py, O(T*k) FLOPs);
    # "dense" = every expert on every token, zero-gated (O(T*E), no drops).
    moe_impl: str = "sparse"
    moe_capacity_factor: float = 2.0
    # GPT-Next/Nemotron architecture knobs (reference serves this family
    # as its second ensemble, ensemble_models/gptnext/ + conversion via
    # model_server/conversion/nemo.py:35-65):
    #   norm: "rmsnorm" (llama) | "layernorm1p" (NeMo's zero-centered
    #         LayerNorm: weights stored as w-1, applied as (1+w)*x_hat+b)
    #   mlp:  "swiglu" (llama gated SiLU) | "squared_relu" (GPT-Next:
    #         relu(x W_up)^2 W_down, no gate projection)
    norm: str = "rmsnorm"
    mlp: str = "swiglu"
    attn_bias: bool = False   # biases on wq/wk/wv/wo
    mlp_bias: bool = False    # biases on the MLP projections

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim


@dataclass(frozen=True)
class EncoderConfig:
    """BERT-style bidirectional encoder (e5-large-v2 geometry)."""
    vocab_size: int = 30522
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_layers: int = 24
    num_heads: int = 16
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12


# ---------------------------------------------------------------------------
# Named registry. Names mirror what the reference's chain server configures
# (reference: deploy/compose/config.yaml model_name entries).
# ---------------------------------------------------------------------------

LLAMA2_7B = LlamaConfig()
LLAMA2_13B = LlamaConfig(hidden_size=5120, intermediate_size=13824,
                         num_layers=40, num_heads=40, num_kv_heads=40)
LLAMA2_70B = LlamaConfig(hidden_size=8192, intermediate_size=28672,
                         num_layers=80, num_heads=64, num_kv_heads=8)
CODELLAMA_13B = replace(LLAMA2_13B, vocab_size=32016, rope_theta=1_000_000.0,
                        max_position_embeddings=16384)
MIXTRAL_8X7B = LlamaConfig(hidden_size=4096, intermediate_size=14336,
                           num_layers=32, num_heads=32, num_kv_heads=8,
                           rope_theta=1_000_000.0,
                           max_position_embeddings=32768,
                           num_experts=8, num_experts_per_tok=2)

# GPT-Next / Nemotron-8B (the reference's second served family:
# ensemble_models/gptnext/, docs/rag/support_matrix.md:14 sizing;
# nemotron_config.yaml deployment). Rotary attention, zero-centered
# LayerNorm, squared-ReLU non-gated MLP, untied embeddings, 256k
# SentencePiece vocab.
NEMOTRON_8B = LlamaConfig(vocab_size=256000, hidden_size=4096,
                          intermediate_size=16384, num_layers=32,
                          num_heads=32, num_kv_heads=32, head_dim=128,
                          max_position_embeddings=4096,
                          norm="layernorm1p", mlp="squared_relu",
                          attn_bias=False, mlp_bias=False)
GPTNEXT_TINY = LlamaConfig(vocab_size=512, hidden_size=128,
                           intermediate_size=256, num_layers=2,
                           num_heads=4, num_kv_heads=4, head_dim=32,
                           max_position_embeddings=512,
                           norm="layernorm1p", mlp="squared_relu")

# Small geometries for tests/benchmarks on limited hardware.
LLAMA_TINY = LlamaConfig(vocab_size=512, hidden_size=128, intermediate_size=352,
                         num_layers=2, num_heads=4, num_kv_heads=2, head_dim=32,
                         max_position_embeddings=512)
# The golden-tiny geometry: real 32k-vocab tokenizer + TRAINED weights
# (tools/make_golden_checkpoint.py trains it on the repo docs; the
# committed checkpoint under tests/fixtures/golden_tiny/ is the CI gate
# for real-vocab detokenization and quantization quality — the coverage
# random-init weights structurally cannot give).
GOLDEN_TINY = LlamaConfig(vocab_size=32000, hidden_size=64,
                          intermediate_size=176, num_layers=2,
                          num_heads=4, num_kv_heads=2, head_dim=16,
                          max_position_embeddings=512,
                          tie_word_embeddings=False)
LLAMA_1B = LlamaConfig(vocab_size=32000, hidden_size=2048,
                       intermediate_size=5632, num_layers=22,
                       num_heads=32, num_kv_heads=4, head_dim=64)

E5_LARGE_V2 = EncoderConfig()
ENCODER_TINY = EncoderConfig(vocab_size=512, hidden_size=64,
                             intermediate_size=128, num_layers=2, num_heads=4,
                             max_position_embeddings=128)

MODEL_REGISTRY: dict[str, LlamaConfig] = {
    "llama-2-7b-chat": LLAMA2_7B,
    "llama-2-13b-chat": LLAMA2_13B,
    "llama-2-70b-chat": LLAMA2_70B,
    "codellama-13b-instruct": CODELLAMA_13B,
    "mixtral-8x7b-instruct": MIXTRAL_8X7B,
    "nemotron-8b-chat": NEMOTRON_8B,
    "gptnext-tiny": GPTNEXT_TINY,
    "llama-tiny": LLAMA_TINY,
    "golden-tiny": GOLDEN_TINY,
    "llama-1b": LLAMA_1B,
}

ENCODER_REGISTRY: dict[str, EncoderConfig] = {
    "intfloat/e5-large-v2": E5_LARGE_V2,
    "e5-large-v2": E5_LARGE_V2,
    "encoder-tiny": ENCODER_TINY,
}


def get_model_config(name: str) -> LlamaConfig:
    try:
        return MODEL_REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown model {name!r}; known: {sorted(MODEL_REGISTRY)}") from None
