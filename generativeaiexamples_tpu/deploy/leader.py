"""Lease-based leader election for the operator.

The reference's manager gets leader election from controller-runtime
(reference: deploy/k8s-operator/kube-trailblazer/main.go — the
``ctrl.NewManager`` options carry the election toggles); this is the
same coordination.k8s.io/v1 Lease protocol over the repo's
``KubeInterface``:

- a single ``Lease`` object names the active holder
  (``spec.holderIdentity``) and its expiry window
  (``renewTime + leaseDurationSeconds``);
- acquiring means writing the Lease CARRYING the observed
  ``resourceVersion`` — optimistic concurrency makes simultaneous
  takeovers race safely (the loser's write raises ``ConflictError``);
- the holder renews within the window; a crashed holder's lease simply
  expires and the next candidate takes over.

The protocol needs only apply/get, so it runs against any
``KubeInterface`` — including ``InMemoryKube``, whose resourceVersion
conflicts make the race paths unit-testable without a cluster.
"""

from __future__ import annotations

import datetime
import time
from typing import Callable, Optional

from .kube import ConflictError, KubeInterface, ObjKey

LEASE_API = "coordination.k8s.io/v1"


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def _fmt(ts: datetime.datetime) -> str:
    # MicroTime, the Lease spec's timestamp format
    return ts.strftime("%Y-%m-%dT%H:%M:%S.%f") + "Z"


def _parse(ts: str) -> Optional[datetime.datetime]:
    for fmt in ("%Y-%m-%dT%H:%M:%S.%fZ", "%Y-%m-%dT%H:%M:%SZ"):
        try:
            return datetime.datetime.strptime(ts, fmt).replace(
                tzinfo=datetime.timezone.utc)
        except ValueError:
            continue
    return None


class LeaderElector:
    """Acquire/renew a Lease; callbacks fire on gain/loss.

    ``lease_seconds`` is the validity window; renewals should happen at
    ``renew_seconds`` (< lease_seconds) intervals. One elector instance
    per candidate process.
    """

    def __init__(self, kube: KubeInterface, identity: str,
                 name: str = "tpu-llm-operator",
                 namespace: str = "kube-system",
                 lease_seconds: int = 15,
                 clock: Callable[[], datetime.datetime] = _now):
        self.kube = kube
        self.identity = identity
        self.key: ObjKey = (LEASE_API, "Lease", namespace, name)
        self.lease_seconds = lease_seconds
        self.is_leader = False
        self._clock = clock

    # ------------------------------------------------------------ protocol

    def _lease_obj(self, current: Optional[dict]) -> dict:
        meta: dict = {"name": self.key[3], "namespace": self.key[2]}
        if current is not None:
            rv = current.get("metadata", {}).get("resourceVersion")
            if rv is not None:
                meta["resourceVersion"] = rv  # optimistic-concurrency guard
        transitions = 0
        if current is not None:
            spec = current.get("spec", {})
            transitions = int(spec.get("leaseTransitions") or 0)
            if spec.get("holderIdentity") not in (None, "", self.identity):
                transitions += 1
        return {
            "apiVersion": LEASE_API, "kind": "Lease", "metadata": meta,
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": self.lease_seconds,
                "renewTime": _fmt(self._clock()),
                "leaseTransitions": transitions,
            },
        }

    def _expired(self, lease: dict) -> bool:
        spec = lease.get("spec", {})
        renew = _parse(str(spec.get("renewTime", "")))
        if renew is None:
            return True
        dur = int(spec.get("leaseDurationSeconds") or self.lease_seconds)
        return self._clock() > renew + datetime.timedelta(seconds=dur)

    def try_acquire(self) -> bool:
        """One acquisition/renewal attempt; returns current leadership."""
        current = self.kube.get(self.key)
        holder = (current or {}).get("spec", {}).get("holderIdentity")
        if current is not None and holder not in (None, "", self.identity) \
                and not self._expired(current):
            self.is_leader = False
            return False
        try:
            self.kube.apply(self._lease_obj(current))
        except ConflictError:
            # lost the takeover race; the winner's renewTime governs now
            self.is_leader = False
            return False
        self.is_leader = True
        return True

    def release(self) -> None:
        """Drop the lease on clean shutdown so the next candidate need
        not wait out the expiry window."""
        if not self.is_leader:
            return
        current = self.kube.get(self.key)
        if current is not None and current.get("spec", {}).get(
                "holderIdentity") == self.identity:
            obj = self._lease_obj(current)
            obj["spec"]["holderIdentity"] = ""
            try:
                self.kube.apply(obj)
            except ConflictError:
                pass  # someone already took it; nothing to release
        self.is_leader = False

    # ------------------------------------------------------------ run loop

    def run(self, while_leading: Callable[..., None],
            renew_seconds: float = 5.0,
            retry_seconds: float = 2.0,
            stop: Optional[Callable[[], bool]] = None) -> None:
        """Block until leadership, then call ``while_leading()`` in a
        loop while a BACKGROUND thread renews the lease every
        ``renew_seconds`` — the callback may block for a full
        watch/resync window (typically longer than the lease duration),
        and without concurrent renewal every cycle would expire the
        lease mid-reconcile and hand a standby a split brain. A failed
        renewal drops ``is_leader``; the loop stops invoking the
        callback after the cycle in flight.

        Leadership loss is additionally propagated INTO the in-flight
        cycle: a ``while_leading`` that accepts an argument receives a
        ``lost() -> bool`` callable, flipped by the renewer the moment a
        renewal fails. Callbacks are expected to poll it between work
        items and to tear down blocking streams (watch windows) when it
        flips — bounding the old-leader/new-leader overlap to roughly
        one renew interval instead of a full watch/resync window
        (ADVICE r5 #2; a zero-argument callback keeps the legacy
        cycle-granular behavior)."""
        import inspect
        import threading
        try:
            takes_lost = bool(inspect.signature(while_leading).parameters)
        except (TypeError, ValueError):  # builtins/C callables: legacy path
            takes_lost = False

        def lost() -> bool:
            return not self.is_leader or bool(stop and stop())

        try:
            while not (stop and stop()):
                if not self.try_acquire():
                    time.sleep(retry_seconds)
                    continue
                done = threading.Event()

                def renew() -> None:
                    while not done.wait(renew_seconds):
                        if not self.try_acquire():
                            return  # is_leader already False; lost() True
                renewer = threading.Thread(target=renew, daemon=True)
                renewer.start()
                try:
                    while self.is_leader and not (stop and stop()):
                        if takes_lost:
                            while_leading(lost)
                        else:
                            while_leading()
                finally:
                    done.set()
                    renewer.join(timeout=renew_seconds + 1)
        finally:
            self.release()
