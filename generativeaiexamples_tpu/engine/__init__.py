"""The serving engine: continuous batching over a slotted KV cache.

This package replaces the reference's entire TRT-LLM serving core — the
Triton C++ backend with inflight fused batching, paged KV, and decoupled
streaming (reference: ensemble_models/llama/tensorrt_llm/config.pbtxt.j2,
model_server/server.py:40-71) — with a jit-compiled JAX program driven by a
host-side scheduler thread.
"""

from .sampling_params import SamplingParams
from .engine import Engine, EngineConfig
from .prefix_cache import PrefixCache

__all__ = ["SamplingParams", "Engine", "EngineConfig", "PrefixCache"]
