"""Vector retrieval: first-party stores + external-engine connectors.

The TPU-native answer to the reference's vector-store layer
(reference: common/utils.py:143-225 wires Milvus GPU_IVF_FLAT, FAISS,
pgvector). Components:

- ``store``      VectorStore interface + factory.
- ``exact``      Exact top-k store (numpy / native C++ / TPU matmul backends).
- ``ivf``        IVF-Flat ANN store (nlist/nprobe parity with the reference's
                 Milvus GPU_IVF_FLAT defaults, nlist=64 nprobe=16).
- ``tpu_search`` On-device brute-force top-k via jit matmul + lax.top_k.
- ``native``     C++ kernels (OpenMP) behind ctypes, compiled on demand.
- ``connectors`` Gated Milvus / pgvector client stores.
- ``docstore``   DocumentIndex: embedder + store + text/metadata persistence.
"""

from .store import SearchHit, VectorStore, get_vector_store
from .exact import ExactStore
from .ivf import IVFFlatStore
from .docstore import Document, DocumentIndex

__all__ = [
    "SearchHit", "VectorStore", "get_vector_store", "ExactStore",
    "IVFFlatStore", "Document", "DocumentIndex",
]
