"""JAX model definitions and checkpoint importers."""
