"""Flight-recorder tests: timeline ring semantics, recorder thread
safety, request-ID adoption/propagation, the /debug/requests endpoint,
and finish/cancel reasons recorded end to end through a real engine."""

import asyncio
import json
import threading
import time

import pytest

import jax
import jax.numpy as jnp
from aiohttp.test_utils import TestClient, TestServer

from generativeaiexamples_tpu.obs import flight
from generativeaiexamples_tpu.obs.flight import (FlightRecorder, Timeline,
                                                 adopt_request_id)


# ----------------------------------------------------------- ring basics

def test_timeline_ring_eviction_and_dropped_count():
    tl = Timeline("r1", event_cap=8)
    for i in range(20):
        tl.event(f"e{i}", i)
    events = tl.events_snapshot()
    assert len(events) == 8
    # oldest were overwritten: only the last cap events survive, in order
    assert [e[2] for e in events] == [f"e{i}" for i in range(12, 20)]
    assert tl.to_dict()["events_dropped"] == 12


def test_timeline_value_conventions_render():
    tl = Timeline("r2")
    tl.stage("prefill", 0.25)          # float -> duration
    tl.event("decode_round", 16)       # int -> count
    tl.event("finish", "eos")          # str -> annotation
    tl.event("engine_submit")          # None -> marker
    rendered = {e["event"]: e for e in tl.to_dict()["events"]}
    assert rendered["prefill"]["dur_ms"] == 250.0
    assert rendered["decode_round"]["value"] == 16
    assert rendered["finish"]["value"] == "eos"
    assert "value" not in rendered["engine_submit"]
    assert tl.stage_durations() == {"prefill": 0.25}


def test_recorder_begin_idempotent_and_completed_ring_bounded():
    rec = FlightRecorder(completed_cap=16, event_cap=8)
    tl = rec.begin("shared")
    assert rec.begin("shared") is tl          # chain + engine share one
    # an EDGE seeing the same client ID while the first is in flight is
    # a different request: fresh=True disambiguates instead of merging
    dup = rec.begin("shared", fresh=True)
    assert dup is not tl and dup.request_id == "shared#2"
    rec.complete(dup)
    rec.complete(tl)
    rec.complete(tl)                          # idempotent
    assert rec.find("shared") is tl
    for i in range(40):
        rec.complete(rec.begin(f"r{i}"))
    snap = rec.snapshot(limit=100)
    assert snap["completed_retained"] == 16
    assert len(snap["completed"]) == 16
    assert rec.find("shared") is None         # evicted from the ring
    assert rec.find("r39") is not None


def test_recorder_thread_safety_under_concurrent_append_and_scrape():
    """Scheduler-thread + harvest-thread appends racing a /debug scraper
    and a begin/complete churn: no exception, bounded structures, every
    surviving event well-formed."""
    rec = FlightRecorder(completed_cap=32, event_cap=16)
    tl = rec.begin("hot")
    stop = threading.Event()
    errors = []

    def appender(name):
        try:
            while not stop.is_set():
                tl.stage(name, 0.001)
                tl.event("decode_round", 8)
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    def churner():
        try:
            i = 0
            while not stop.is_set():
                rec.complete(rec.begin(f"churn-{i}"))
                i += 1
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    def scraper():
        try:
            while not stop.is_set():
                snap = rec.snapshot()
                json.dumps(snap)  # JSON-able under concurrent writes
                for t in snap["in_flight"] + snap["completed"]:
                    for e in t["events"]:
                        assert "event" in e and "t_ms" in e
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = ([threading.Thread(target=appender, args=(f"s{i}",))
                for i in range(2)]
               + [threading.Thread(target=churner),
                  threading.Thread(target=scraper)])
    for t in threads:
        t.start()
    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not errors, errors
    assert len(rec.snapshot(limit=1000)["completed"]) <= 32
    # ring still ordered after the stampede
    seqs = [e[0] for e in tl.events_snapshot()]
    assert seqs == sorted(seqs)


def test_adopt_request_id():
    assert adopt_request_id({"X-Request-ID": "abc-123"}) == "abc-123"
    # traceparent trace-id adopted when no explicit header
    tp = "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01"
    assert adopt_request_id({"traceparent": tp}) == \
        "0af7651916cd43dd8448eb211c80319c"
    # sanitized: quotes/braces stripped, length capped
    rid = adopt_request_id({"X-Request-ID": 'a"b{c}' + "x" * 500})
    assert '"' not in rid and "{" not in rid and len(rid) <= 128
    # minted when absent — via the caller's minter (the OpenAI surface
    # keeps its cmpl- id shape on malformed/absent headers)
    assert adopt_request_id({}) and adopt_request_id(None)
    assert adopt_request_id({"X-Request-ID": "  "},
                            mint=lambda: "cmpl-x") == "cmpl-x"
    assert adopt_request_id({"traceparent": "garbage"},
                            mint=lambda: "cmpl-y") == "cmpl-y"


# --------------------------------------------------- /debug/requests HTTP

def _run(coro):
    return asyncio.get_event_loop_policy().new_event_loop() \
        .run_until_complete(coro)


def test_debug_requests_endpoint_inflight_vs_completed(monkeypatch):
    """A mid-generation request shows under in_flight with the adopted
    X-Request-ID (echoed in the response header); after the stream
    drains it moves to completed with its finish reason."""
    from generativeaiexamples_tpu.chains.base import BaseExample
    from generativeaiexamples_tpu.chains.server import create_app

    rec = FlightRecorder(completed_cap=16)
    monkeypatch.setattr(flight, "RECORDER", rec)

    release = threading.Event()

    class SlowExample(BaseExample):
        def llm_chain(self, context, question, num_tokens):
            yield "first "
            release.wait(timeout=30)
            yield "second"

        def rag_chain(self, prompt, num_tokens):
            yield from self.llm_chain("", prompt, num_tokens)

        def ingest_docs(self, data_dir, filename):
            pass

    async def fn():
        client = TestClient(TestServer(create_app(SlowExample())))
        await client.start_server()
        try:
            resp = await client.post(
                "/generate",
                json={"question": "q", "use_knowledge_base": False,
                      "num_tokens": 8},
                headers={"X-Request-ID": "dbg-1"})
            assert resp.headers["X-Request-ID"] == "dbg-1"
            await resp.content.read(6)          # first chunk arrived

            dbg = await (await client.get("/debug/requests")).json()
            inflight = {t["request_id"]: t for t in dbg["in_flight"]}
            assert "dbg-1" in inflight
            assert not inflight["dbg-1"]["done"]
            assert inflight["dbg-1"]["meta"]["route"] == "/generate"

            release.set()
            await resp.read()                   # drain to completion

            for _ in range(100):                # worker finishes async
                dbg = await (await client.get(
                    "/debug/requests?limit=5")).json()
                done = {t["request_id"]: t for t in dbg["completed"]}
                if "dbg-1" in done:
                    break
                await asyncio.sleep(0.05)
            assert "dbg-1" in done
            assert done["dbg-1"]["meta"]["finish"] == "done"
            assert not any(t["request_id"] == "dbg-1"
                           for t in dbg["in_flight"])

            # bad limit is a 400, not a 500
            assert (await client.get("/debug/requests?limit=x")).status \
                == 400
        finally:
            release.set()
            await client.close()
    _run(fn())


# ------------------------------------------------------- engine end-to-end

from generativeaiexamples_tpu.engine import (Engine, EngineConfig,  # noqa: E402
                                             SamplingParams)
from generativeaiexamples_tpu.models import llama  # noqa: E402
from generativeaiexamples_tpu.models.configs import LlamaConfig  # noqa: E402
from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer  # noqa: E402

CFG = LlamaConfig(vocab_size=259 + 5, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  max_position_embeddings=256)

ENGINE_CFG = EngineConfig(max_slots=2, max_input_length=32,
                          max_output_length=16, prefill_buckets=(16, 32),
                          dtype="float32", max_queue=16,
                          steps_per_round=4)


@pytest.fixture(scope="module")
def engine():
    params = llama.init_params(CFG, jax.random.key(7), dtype=jnp.float32)
    eng = Engine(params, CFG, ByteTokenizer(), ENGINE_CFG)
    eng.flight = FlightRecorder(completed_cap=64)
    with eng:
        yield eng


def test_request_id_stamped_on_stream_and_timeline(engine):
    stream = engine.submit(
        engine.tokenizer.encode("hello"),
        SamplingParams(max_tokens=6, top_k=1, ignore_eos=True),
        request_id="prop-1")
    stream.text()
    assert stream.request_id == "prop-1"
    tl = engine.flight.find("prop-1")
    assert tl is not None and tl.done
    names = [e[2] for e in tl.events_snapshot()]
    for expected in ("engine_submit", "engine_admit_pickup",
                     "engine_admit_dispatch", "engine_first_readback",
                     "engine_ttft", "finish"):
        assert expected in names, (expected, names)
    assert tl.meta["finish"] == "length"
    assert tl.meta["generated"] == 6
    assert tl.meta["prompt_tokens"] == len(engine.tokenizer.encode("hello"))
    assert tl.meta["ttft_ms"] is not None
    # a decode_round token-count event exists (per ROUND, not per token).
    # The harvest worker appends it just AFTER delivering the round's
    # tokens, so it can land microseconds after text() returns — poll.
    deadline = time.monotonic() + 10
    rounds: list = []
    while not rounds and time.monotonic() < deadline:
        rounds = [e[3] for e in tl.events_snapshot()
                  if e[2] == "decode_round"]
        if not rounds:
            time.sleep(0.02)
    assert rounds and sum(rounds) <= 6


def test_request_id_adopted_from_bound_context(engine):
    """The chain-server path: the ID bound on the calling context (the
    adopted X-Request-ID) reaches Engine.submit without being passed —
    header in, same ID on the engine stream and its timeline. The EDGE
    owns completion: the engine sub-call annotates but must not retire
    the request's timeline (agent chains run several engine calls per
    request)."""
    tl_edge = engine.flight.begin("ctx-77")
    token = flight.bind(tl_edge)
    try:
        stream = engine.submit(
            engine.tokenizer.encode("abc"),
            SamplingParams(max_tokens=4, top_k=1, ignore_eos=True))
    finally:
        flight.unbind(token)
    stream.text()
    assert stream.request_id == "ctx-77"
    assert stream.timeline is tl_edge          # shared, not a duplicate
    assert not stream.owns_timeline
    deadline = time.monotonic() + 10           # harvest thread annotates
    while tl_edge.meta.get("finish") is None \
            and time.monotonic() < deadline:
        time.sleep(0.02)
    assert tl_edge.meta["finish"] == "length"
    assert not tl_edge.done                    # edge completes, not engine
    # second sub-call on the same request timeline: stats accumulate
    token = flight.bind(tl_edge)
    try:
        engine.submit(
            engine.tokenizer.encode("de"),
            SamplingParams(max_tokens=3, top_k=1, ignore_eos=True)).text()
    finally:
        flight.unbind(token)
    deadline = time.monotonic() + 10
    while tl_edge.meta.get("generated", 0) < 7 \
            and time.monotonic() < deadline:
        time.sleep(0.02)
    assert tl_edge.meta["generated"] == 4 + 3
    engine.flight.complete(tl_edge)            # the edge's finally
    assert engine.flight.find("ctx-77").done


def test_cancel_reason_recorded(engine):
    stream = engine.submit(
        engine.tokenizer.encode("zzzz"),
        SamplingParams(max_tokens=12, top_k=1, ignore_eos=True),
        request_id="cxl-1")
    stream.cancel()
    stream.text()
    assert stream.finish_reason == "cancelled"
    tl = engine.flight.find("cxl-1")
    assert tl.done and tl.meta["finish"] == "cancelled"
    finishes = [e[3] for e in tl.events_snapshot() if e[2] == "finish"]
    assert finishes == ["cancelled"]


def test_queue_full_rejection_recorded(engine):
    """A SchedulerFullError'd submit retires its timeline as 'rejected'
    instead of leaking a forever-in-flight entry."""
    import queue as _q

    from generativeaiexamples_tpu.utils.errors import SchedulerFullError

    full_q: "_q.Queue" = _q.Queue(maxsize=1)
    full_q.put_nowait(("sentinel", None))
    orig = engine._pending
    engine._pending = full_q
    try:
        with pytest.raises(SchedulerFullError):
            engine.submit(engine.tokenizer.encode("x"),
                          SamplingParams(max_tokens=2),
                          request_id="rej-1")
    finally:
        engine._pending = orig
    tl = engine.flight.find("rej-1")
    assert tl is not None and tl.done and tl.meta["finish"] == "rejected"
    assert "rej-1" not in {t.request_id
                           for t in engine.flight._inflight.values()}


def test_slow_request_dump_carries_request_id(engine, caplog):
    """SLO breach → one structured slow_request log line whose JSON
    payload carries the same request ID as the timeline."""
    import logging

    rec = engine.flight
    old_ttft = rec.slo_ttft_ms
    rec.slo_ttft_ms = 0.000001  # everything breaches
    try:
        with caplog.at_level(logging.WARNING,
                             logger="generativeaiexamples_tpu.obs.flight"):
            engine.submit(engine.tokenizer.encode("slow"),
                          SamplingParams(max_tokens=2, top_k=1,
                                         ignore_eos=True),
                          request_id="slo-1").text()
            # the dump fires on the harvest thread just after the stream
            # drains — poll briefly for the record
            deadline = time.monotonic() + 10
            lines: list = []
            while time.monotonic() < deadline and not lines:
                lines = [r.getMessage() for r in caplog.records
                         if r.getMessage().startswith("slow_request ")]
                if not lines:
                    time.sleep(0.02)
    finally:
        rec.slo_ttft_ms = old_ttft
    assert lines, caplog.records
    payload = json.loads(lines[-1].split(" ", 1)[1])
    assert payload["request_id"] == "slo-1"
    assert payload["timeline"]["request_id"] == "slo-1"


def test_span_replay_emits_engine_stage_spans(engine, monkeypatch):
    """With tracing on, completion replays duration events as spans
    carrying the request ID — engine stages join the request's trace."""
    from generativeaiexamples_tpu.obs import tracing

    spans = []

    class FakeSpan:
        def __init__(self, name, attributes):
            self.name = name
            self.attributes = attributes

        def end(self, end_time=None):
            pass

    class FakeTracer:
        def start_span(self, name, context=None, start_time=None,
                       attributes=None):
            span = FakeSpan(name, dict(attributes or {}))
            spans.append(span)
            return span

    monkeypatch.setattr(tracing, "_enabled_override", True)
    monkeypatch.setattr(tracing, "_tracer", FakeTracer())
    engine.submit(engine.tokenizer.encode("sp"),
                  SamplingParams(max_tokens=2, top_k=1, ignore_eos=True),
                  request_id="span-1").text()
    # completion happens on the harvest thread; wait for the replay
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not any(
            s.attributes.get("request.id") == "span-1" for s in spans):
        time.sleep(0.02)
    mine = [s for s in spans if s.attributes.get("request.id") == "span-1"]
    assert {"engine_admit_dispatch", "engine_ttft"} <= {s.name
                                                        for s in mine}
