"""Test harness: run everything on a virtual 8-device CPU mesh.

This is the JAX analogue of the reference's envtest trick (a real
kube-apiserver without a cluster; reference:
deploy/k8s-operator/kube-trailblazer/controllers/suite_test.go:50-60) —
multi-chip behavior without chips, via
``--xla_force_host_platform_device_count``.

Must set env BEFORE jax is imported anywhere.
"""

import os
import sys

# Force CPU: the ambient env pins JAX_PLATFORMS to the real TPU backend
# (and a sitecustomize re-registers it), so the env var alone is not enough —
# jax.config must be updated post-import, before any backend is initialized.
# Tests need the 8-device virtual CPU mesh (and fp32 determinism).
os.environ["JAX_PLATFORMS"] = "cpu"
import re as _re  # noqa: E402

_flags = os.environ.get("XLA_FLAGS", "")
_flags = _re.sub(r"--xla_force_host_platform_device_count=\d+", "", _flags)
os.environ["XLA_FLAGS"] = (
    _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import contextlib  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    import jax
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def repo_root():
    import pathlib
    return pathlib.Path(__file__).resolve().parent.parent


@contextlib.contextmanager
def serve_app(app, timeout: float = 30.0):
    """Run an aiohttp app on an ephemeral port in a background thread;
    yields the base URL. Shared by every test that drives a live HTTP
    surface (score endpoint, real-weights gate, ...)."""
    import asyncio
    import threading

    from aiohttp import web

    loop = asyncio.new_event_loop()
    box: dict = {}
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)

        async def boot():
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            box["port"] = runner.addresses[0][1]
        loop.run_until_complete(boot())
        started.set()
        loop.run_forever()

    threading.Thread(target=run, daemon=True).start()
    assert started.wait(timeout), "HTTP server failed to boot in time"
    try:
        yield f"http://127.0.0.1:{box['port']}"
    finally:
        loop.call_soon_threadsafe(loop.stop)
