"""Sharding rules for the model param trees (megatron-style TP).

Replaces the reference's per-rank weight splitting
(reference: conversion_scripts/llama/weight.py:141-148 ``split`` slices each
tensor per MPI rank at import time). Here the full logical tree is annotated
with ``PartitionSpec``s and ``jax.device_put`` / GSPMD does the physical
placement — one code path for any mesh shape.

Rules (leading axis of every layer tensor is L, sharded over ``pp`` when
pipeline parallelism is on):
  wq/wk/wv  (L, D, heads*hd)  → column-parallel: shard out dim over tp
  wo        (L, heads*hd, D)  → row-parallel: shard in dim over tp
  w_gate/up (L, D, F)         → column-parallel
  w_down    (L, F, D)         → row-parallel
  embed     (V, D)            → shard V over tp (vocab-parallel)
  lm_head   (D, V)            → shard V over tp
  MoE experts (L, E, ...)     → shard E over ep, then tp on the inner dims
XLA inserts the all-reduce after row-parallel matmuls — the compiled
equivalent of the reference's NCCL all-reduce plugin
(reference: build.py:341-345 ``use_custom_all_reduce``).

GQA note: when tp > num_kv_heads the reference duplicates KV weights
(weight.py:150-157). Here ``kv_tp_axis`` degrades wk/wv to replicated in
that case and XLA re-partitions the attention einsum itself.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.configs import LlamaConfig

Specs = dict[str, Any]


def _axis_on(mesh: Mesh, name: str) -> Optional[str]:
    """Axis name if it exists in the mesh with size > 1, else None."""
    return name if mesh.shape.get(name, 1) > 1 else None


def llama_param_specs(cfg: LlamaConfig, mesh: Mesh) -> Specs:
    tp = _axis_on(mesh, "tp")
    pp = _axis_on(mesh, "pp")
    ep = _axis_on(mesh, "ep")
    # KV projections can only shard over tp if heads divide evenly.
    kv_tp = tp if tp and cfg.num_kv_heads % mesh.shape["tp"] == 0 else None
    q_tp = tp if tp and cfg.num_heads % mesh.shape["tp"] == 0 else None

    layers: Specs = {
        "attn_norm": P(pp, None),
        "mlp_norm": P(pp, None),
        "wq": P(pp, None, q_tp),
        "wk": P(pp, None, kv_tp),
        "wv": P(pp, None, kv_tp),
        "wo": P(pp, q_tp, None),
    }
    # GPT-Next/Nemotron extras (norm biases, projection biases): biases
    # shard like their projection's output dim.
    if cfg.norm == "layernorm1p":
        layers["attn_norm_b"] = P(pp, None)
        layers["mlp_norm_b"] = P(pp, None)
    if cfg.attn_bias:
        layers.update({"bq": P(pp, q_tp), "bk": P(pp, kv_tp),
                       "bv": P(pp, kv_tp), "bo": P(pp, None)})
    if cfg.num_experts:
        layers.update({
            "router": P(pp, None, None),
            "w_gate": P(pp, ep, None, tp),
            "w_up": P(pp, ep, None, tp),
            "w_down": P(pp, ep, tp, None),
        })
    elif cfg.mlp == "squared_relu":
        layers.update({
            "w_up": P(pp, None, tp),
            "w_down": P(pp, tp, None),
        })
        if cfg.mlp_bias:
            layers.update({"b_up": P(pp, tp), "b_down": P(pp, None)})
    else:
        layers.update({
            "w_gate": P(pp, None, tp),
            "w_up": P(pp, None, tp),
            "w_down": P(pp, tp, None),
        })
    specs: Specs = {
        "embed": P(tp, None),
        "layers": layers,
        "final_norm": P(None),
    }
    if cfg.norm == "layernorm1p":
        specs["final_norm_b"] = P(None)
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = P(None, tp)
    return specs


def kv_cache_spec(cfg: LlamaConfig, mesh: Mesh) -> Specs:
    """Cache (L, B, T, KV, hd): batch over dp, KV heads over tp."""
    tp = _axis_on(mesh, "tp")
    dp = _axis_on(mesh, "dp")
    pp = _axis_on(mesh, "pp")
    kv_tp = tp if tp and cfg.num_kv_heads % mesh.shape["tp"] == 0 else None
    spec = P(pp, dp, None, kv_tp, None)
    return {"k": spec, "v": spec}


def paged_kv_cache_spec(cfg: LlamaConfig, mesh: Mesh,
                        quantized: bool = False) -> Specs:
    """Paged cache (L, N, KV, page, hd): KV heads over tp, pages replicated.

    The page pool has no batch axis (slots share it through block tables),
    so dp does not appear; layers shard over pp like the params.
    int8-KV mode adds per-row scale pools (L, N, KV, page) — same sharding
    minus the head dim (ops/kv_quant.py).
    """
    tp = _axis_on(mesh, "tp")
    pp = _axis_on(mesh, "pp")
    kv_tp = tp if tp and cfg.num_kv_heads % mesh.shape["tp"] == 0 else None
    spec = P(pp, None, kv_tp, None, None)
    specs = {"k": spec, "v": spec}
    if quantized:
        specs["ks"] = specs["vs"] = P(pp, None, kv_tp, None)
    return specs


def activation_spec(mesh: Mesh) -> P:
    """Token/hidden activations: batch over dp, replicated over tp."""
    return P(_axis_on(mesh, "dp"), None)


def shard_params(params: Any, mesh: Mesh, specs: Any) -> Any:
    """Place a param tree onto the mesh per its specs.

    Quantized leaves (``{"q"|"q4", "scale"}`` dicts from ops.quant) reuse
    the raw weight's spec: the int tensor takes it verbatim; the
    per-output-channel scale (one rank lower, reduction axis gone) takes
    the spec minus its second-to-last axis.
    """
    from ..ops.quant import is_quantized

    def place(x, s):
        return jax.device_put(x, NamedSharding(mesh, s))

    def walk(p: Any, s: Any) -> Any:
        if isinstance(p, dict):
            if is_quantized(p):
                w_spec = tuple(s)
                scale_spec = (P(*(w_spec[:-2] + w_spec[-1:]))
                              if len(w_spec) >= 2 else P())

                def leaf_spec(k):
                    if k in ("q", "q4"):
                        return s
                    if k in ("gscale", "gbias"):
                        # (..., G, N): same rank as the weight — the
                        # group axis stands where K stood
                        return P(*w_spec)
                    if k == "pre_scale":
                        return (P(*w_spec[:-1]) if len(w_spec) >= 1
                                else P())
                    return scale_spec
                return {k: place(v, leaf_spec(k)) for k, v in p.items()}
            return {k: walk(v, s[k]) for k, v in p.items()}
        return place(p, s)

    return walk(params, specs)
