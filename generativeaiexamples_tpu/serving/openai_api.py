"""OpenAI-style HTTP API over the engine + embedder.

Endpoint parity with the reference's NeMo Inference MS connector targets
(reference: integrations/langchain/llms/nemo_infer.py — ``/v1/completions``
with SSE streaming; embeddings/nemo_embed.py — ``/v1/embeddings`` with
``input_type`` passage/query), plus ``/v1/chat/completions`` and
``/v1/models``. Unlike nemo's cumulative-text SSE (client must diff,
nemo_infer.py:141-156), streams send true deltas.
"""

from __future__ import annotations

import asyncio
import json
import math
import os
import time
import uuid
from typing import Optional

from aiohttp import web

from ..engine.sampling_params import SamplingParams
from ..obs import metrics as obs_metrics
from ..obs.tracing import instrumented
from ..utils.errors import SchedulerFullError
from .streaming import iterate_in_thread


def _openai_error(status: int, err_type: str, message: str,
                  retry_after_s: Optional[float] = None) -> web.Response:
    """OpenAI-shaped error body; ``Retry-After`` on retryable statuses."""
    headers = {}
    if retry_after_s is not None:
        headers["Retry-After"] = str(max(1, int(math.ceil(retry_after_s))))
    return web.json_response(
        {"error": {"type": err_type, "message": message, "code": status}},
        status=status, headers=headers)


def _sampling_from_body(body: dict, max_output: int) -> SamplingParams:
    max_tokens = min(int(body.get("max_tokens", 256)), max_output)
    temperature = float(body.get("temperature", 1.0))
    stop = body.get("stop") or []
    if isinstance(stop, str):  # OpenAI allows a bare string
        stop = [stop]
    return SamplingParams(
        max_tokens=max_tokens,
        temperature=temperature,
        # OpenAI semantics: temperature/top_p drive sampling; top_k
        # unlimited unless the caller uses our extension. (The Triton shim
        # keeps the reference's greedy top_k=1 default instead.)
        top_k=int(body.get("top_k", 0)),
        top_p=float(body.get("top_p", 1.0)),
        repetition_penalty=float(body.get("repetition_penalty", 1.0)),
        random_seed=int(body.get("seed", body.get("random_seed", 0))),
        stop_words=[str(s) for s in stop],
    )


def _completion_payload(rid: str, model: str, text: str,
                        finish: Optional[str], *, kind: str,
                        created: int, usage: Optional[dict] = None,
                        stream_delta: bool = False) -> dict:
    if kind == "chat":
        if stream_delta:
            choice: dict = {"index": 0, "delta": {"content": text},
                            "finish_reason": finish}
        else:
            choice = {"index": 0,
                      "message": {"role": "assistant", "content": text},
                      "finish_reason": finish}
        obj = "chat.completion.chunk" if stream_delta else "chat.completion"
    else:
        choice = {"index": 0, "text": text, "finish_reason": finish}
        obj = "text_completion"
    out = {"id": rid, "object": obj, "created": created, "model": model,
           "choices": [choice]}
    if usage:
        out["usage"] = usage
    return out


def add_openai_routes(app: web.Application, engine, model_name: str,
                      embed_service=None, chat_template: Optional[str] = None,
                      max_output: int = 512) -> None:
    """Mount /v1/* routes for one engine (and optional embedder)."""

    def render_chat(messages: list[dict]) -> str:
        """Llama-2 [INST] chat rendering (parity with the reference's
        prompt templates, common/configuration.py:124-156)."""
        system = ""
        turns: list[str] = []
        for m in messages:
            role, content = m.get("role"), m.get("content", "")
            if role == "system":
                system = f"<<SYS>>\n{content}\n<</SYS>>\n\n"
            elif role == "user":
                turns.append(f"<s>[INST] {system}{content} [/INST]")
                system = ""
            elif role == "assistant":
                turns.append(f" {content} </s>")
        return "".join(turns)

    async def _generate(request: web.Request, kind: str) -> web.StreamResponse:
        body = await request.json()
        if kind == "chat":
            prompt = render_chat(body.get("messages", []))
        else:
            prompt = body.get("prompt", "")
            if isinstance(prompt, list):
                prompt = prompt[0] if prompt else ""
        if not prompt:
            raise web.HTTPUnprocessableEntity(
                text="empty prompt/messages")
        try:
            params = _sampling_from_body(body, max_output)
        except (ValueError, TypeError) as exc:
            raise web.HTTPBadRequest(
                text=f"invalid sampling parameters: {exc}") from exc
        # The completion id doubles as the flight-recorder request ID —
        # adopted (sanitized, same rules as the chain server) from the
        # caller's X-Request-ID/traceparent when sent, so one ID names
        # the API response, the /debug/requests timeline, and the
        # slow-request dump. Passed explicitly (not via contextvar):
        # run_in_executor does not propagate context.
        from ..obs import flight as obs_flight
        rid = obs_flight.adopt_request_id(
            request.headers, mint=lambda: f"cmpl-{uuid.uuid4().hex[:24]}")
        # Per-request deadline (X-Deadline-Ms, env default): passed to
        # the engine EXPLICITLY — run_in_executor does not propagate the
        # contextvar the chain server rides — so queued-past-deadline
        # requests drop before prefill and decode stops when it passes.
        deadline_ms = obs_flight.adopt_deadline_ms(
            request.headers,
            float(os.environ.get("REQUEST_DEADLINE_MS", "0") or 0) or None)
        deadline_t = (time.monotonic() + deadline_ms / 1e3
                      if deadline_ms is not None else None)
        created = int(time.time())
        timer = obs_metrics.RequestTimer(f"serve_{kind}")

        engine.start()
        loop = asyncio.get_running_loop()
        try:
            # Tokenization off the event loop: a long prompt must not stall
            # other in-flight requests on this single-threaded server.
            stream = await loop.run_in_executor(
                None, lambda: engine.stream_text(prompt, params,
                                                 request_id=rid,
                                                 deadline_t=deadline_t))
        except SchedulerFullError as exc:
            # Overload is a 429 with a retry hint, not a 503: the engine
            # is alive, its admission queue is full. Retry-After from the
            # flight recorder's measured queue-wait estimate — retries
            # space to the queue's actual drain time, not a constant.
            _, wait_ms = obs_flight.RECORDER.recent_stage_ms(
                "engine_admit_pickup")
            return _openai_error(429, "rate_limit_error", str(exc),
                                 retry_after_s=max(1.0, wait_ms / 1e3))
        except Exception as exc:  # noqa: BLE001
            return _openai_error(503, "service_unavailable", str(exc))
        # The response id must BE the timeline key: a duplicate
        # in-flight X-Request-ID gets a '#N'-suffixed timeline, and the
        # client must receive the id that /debug/requests answers to.
        rid = stream.request_id

        if body.get("stream"):
            resp = web.StreamResponse(
                headers={"Content-Type": "text/event-stream",
                         "Cache-Control": "no-cache",
                         "X-Request-ID": rid})
            await resp.prepare(request)
            try:
                async for chunk in iterate_in_thread(iter(stream), on_cancel=stream.cancel):
                    # each emitted chunk ≈ one decode step (one token)
                    timer.token(1)
                    payload = _completion_payload(
                        rid, model_name, chunk, None, kind=kind,
                        created=created, stream_delta=True)
                    await resp.write(
                        f"data: {json.dumps(payload)}\n\n".encode())
                final = _completion_payload(rid, model_name, "",
                                            stream.finish_reason, kind=kind,
                                            created=created,
                                            stream_delta=True)
                await resp.write(f"data: {json.dumps(final)}\n\n".encode())
                await resp.write(b"data: [DONE]\n\n")
                await resp.write_eof()
            except (ConnectionResetError, ConnectionError):
                pass  # client went away mid-stream
            finally:
                timer.finish()
            return resp

        text = "".join([c async for c in iterate_in_thread(iter(stream), on_cancel=stream.cancel)])
        timer.token(len(stream.token_ids))
        timer.finish()
        n_prompt = len(await loop.run_in_executor(
            None, engine.tokenizer.encode, prompt))
        usage = {"prompt_tokens": n_prompt,
                 "completion_tokens": len(stream.token_ids),
                 "total_tokens": n_prompt + len(stream.token_ids)}
        return web.json_response(_completion_payload(
            rid, model_name, text, stream.finish_reason, kind=kind,
            created=created, usage=usage))

    @instrumented("v1_completions")
    async def completions(request: web.Request) -> web.StreamResponse:
        return await _generate(request, "completion")

    @instrumented("v1_chat_completions")
    async def chat_completions(request: web.Request) -> web.StreamResponse:
        return await _generate(request, "chat")

    @instrumented("v1_embeddings")
    async def embeddings(request: web.Request) -> web.Response:
        if embed_service is None:
            raise web.HTTPNotImplemented(text="no embedding model loaded")
        body = await request.json()
        inputs = body.get("input", [])
        if isinstance(inputs, str):
            inputs = [inputs]
        # input_type parity with the NeMo retriever API
        # (reference: embeddings/nemo_embed.py:96-102).
        input_type = body.get("input_type", "query")
        loop = asyncio.get_running_loop()
        if input_type == "passage":
            vecs = await loop.run_in_executor(
                None, embed_service.embed_documents, inputs)
        else:
            vecs = await loop.run_in_executor(
                None, lambda: [embed_service.embed_query(t) for t in inputs])
        data = [{"object": "embedding", "index": i,
                 "embedding": [float(x) for x in v]}
                for i, v in enumerate(vecs)]
        return web.json_response(
            {"object": "list", "data": data,
             "model": body.get("model", "e5-large-v2")})

    async def models(request: web.Request) -> web.Response:
        entries = [{"id": model_name, "object": "model",
                    "owned_by": "generativeaiexamples-tpu"}]
        if embed_service is not None:
            entries.append({"id": "embeddings", "object": "model",
                            "owned_by": "generativeaiexamples-tpu"})
        return web.json_response({"object": "list", "data": entries})

    app.router.add_post("/v1/completions", completions)
    app.router.add_post("/v1/chat/completions", chat_completions)
    app.router.add_post("/v1/embeddings", embeddings)
    app.router.add_get("/v1/models", models)
