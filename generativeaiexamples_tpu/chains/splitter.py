"""Token-aware text splitting and context budgeting.

Parity with the reference's chunking — 510 tokens per chunk with 200
overlap on the embedder's tokenizer
(reference: common/utils.py:315-321 ``SentenceTransformersTokenTextSplitter``,
common/configuration.py:83-92) — and with its retrieved-context token cap
(reference: common/utils.py:96-118 ``LimitRetrievedNodesLength`` caps
stuffed context at 1500 tokens).
"""

from __future__ import annotations

import re
from typing import Optional, Sequence

from ..models.tokenizer import ByteTokenizer, Tokenizer

_SENTENCE_RE = re.compile(r"(?<=[.!?;\n])\s+")


class TokenTextSplitter:
    """Sentence-respecting token-window splitter.

    Sentences are packed greedily into windows of ``chunk_size`` tokens;
    consecutive chunks share ``chunk_overlap`` tokens of trailing context.
    A sentence longer than ``chunk_size`` is hard-split on token boundaries.
    """

    def __init__(self, tokenizer: Optional[Tokenizer] = None,
                 chunk_size: int = 510, chunk_overlap: int = 200):
        if chunk_overlap >= chunk_size:
            raise ValueError("chunk_overlap must be < chunk_size")
        self.tok = tokenizer or ByteTokenizer()
        self.chunk_size = chunk_size
        self.chunk_overlap = chunk_overlap

    def _count(self, text: str) -> int:
        return len(self.tok.encode(text, add_bos=False))

    def split_text(self, text: str) -> list[str]:
        text = text.strip()
        if not text:
            return []
        if self._count(text) <= self.chunk_size:
            return [text]

        # Sentence units, hard-splitting any oversized sentence.
        units: list[tuple[str, int]] = []
        for sent in _SENTENCE_RE.split(text):
            if not sent.strip():
                continue
            n = self._count(sent)
            if n <= self.chunk_size:
                units.append((sent, n))
            else:
                ids = self.tok.encode(sent, add_bos=False)
                for s in range(0, len(ids), self.chunk_size):
                    piece = self.tok.decode(ids[s:s + self.chunk_size])
                    units.append((piece, min(self.chunk_size, len(ids) - s)))

        chunks: list[str] = []
        cur: list[tuple[str, int]] = []
        cur_tokens = 0
        for sent, n in units:
            # +1 per join separator so the reassembled chunk stays in budget
            if cur and cur_tokens + n + 1 > self.chunk_size:
                chunks.append(" ".join(s for s, _ in cur))
                # Retain trailing sentences as overlap for continuity.
                keep: list[tuple[str, int]] = []
                kept = 0
                for us, un in reversed(cur):
                    if kept + un + 1 > self.chunk_overlap:
                        break
                    keep.insert(0, (us, un))
                    kept += un + 1
                cur, cur_tokens = keep, kept
            cur.append((sent, n))
            cur_tokens += n + (1 if len(cur) > 1 else 0)
        if cur:
            chunks.append(" ".join(s for s, _ in cur))
        return chunks


def cap_context(texts: Sequence[str], max_tokens: int = 1500,
                tokenizer: Optional[Tokenizer] = None) -> list[str]:
    """Keep the leading documents that fit in the token budget.

    Parity with ``LimitRetrievedNodesLength._postprocess_nodes``
    (reference: common/utils.py:96-118): iterate retrieved docs in rank
    order, stop once the running token total would exceed the cap.
    """
    tok = tokenizer or ByteTokenizer()
    out: list[str] = []
    total = 0
    for text in texts:
        n = len(tok.encode(text, add_bos=False))
        if total + n > max_tokens:
            break
        out.append(text)
        total += n
    return out
