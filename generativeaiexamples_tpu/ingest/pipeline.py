"""The ingest pipeline: source -> extract -> chunk -> embed+store.

Mirrors the reference's VDB-upload pipeline shape (reference:
experimental/streaming_ingest_rag/pipeline.py:60-102 — source pipes into
content extraction into tokenize/embed into WriteToVectorDBStage, with a
MonitorStage reporting throughput between every pair of stages). Here:

- stages are coroutines connected by bounded asyncio queues, so a slow
  embedder backpressures extraction instead of buffering unbounded;
- the store stage batches chunks (count or linger timeout) into the
  jit-compiled batch encoder — one device dispatch per batch, the role
  Triton inference plays in the reference;
- per-stage counters live in the shared metrics registry and in a
  ``PipelineStats`` snapshot (the MonitorStage equivalent).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Optional

from ..chains.readers import read_document
from ..chains.splitter import TokenTextSplitter
from ..obs import metrics as obs_metrics
from ..utils.logging import get_logger
from .sources import SourceItem

logger = get_logger(__name__)

_STOP = object()


@dataclass
class PipelineStats:
    """Per-stage throughput counters (MonitorStage equivalent)."""
    items_in: int = 0
    documents_extracted: int = 0
    chunks: int = 0
    chunks_stored: int = 0
    batches: int = 0
    errors: int = 0
    started: float = field(default_factory=time.monotonic)

    def snapshot(self) -> dict:
        dt = max(time.monotonic() - self.started, 1e-9)
        return {"items_in": self.items_in,
                "documents_extracted": self.documents_extracted,
                "chunks": self.chunks,
                "chunks_stored": self.chunks_stored,
                "batches": self.batches,
                "errors": self.errors,
                "chunks_per_sec": round(self.chunks_stored / dt, 2),
                "elapsed_sec": round(dt, 2)}


class IngestPipeline:
    """source -> extract/chunk -> batch embed+store."""

    def __init__(self, source, index, chunk_size: int = 510,
                 chunk_overlap: int = 200, batch_size: int = 32,
                 linger_sec: float = 1.0, queue_size: int = 64,
                 max_items: Optional[int] = None):
        self.source = source
        self.index = index
        self.splitter = TokenTextSplitter(chunk_size=chunk_size,
                                          chunk_overlap=chunk_overlap)
        self.batch_size = batch_size
        self.linger_sec = linger_sec
        self.queue_size = queue_size
        self.max_items = max_items
        self.stats = PipelineStats()

    # ------------------------------------------------------------- stages

    async def _extract(self, in_q: asyncio.Queue,
                       out_q: asyncio.Queue) -> None:
        loop = asyncio.get_running_loop()
        while True:
            item = await in_q.get()
            if item is _STOP:
                await out_q.put(_STOP)
                return
            try:
                if item.path:
                    text = await loop.run_in_executor(
                        None, read_document, item.path)
                else:
                    text = item.content
                chunks = self.splitter.split_text(text or "")
                self.stats.documents_extracted += 1
                obs_metrics.REGISTRY.counter(
                    "ingest_documents_total",
                    "documents extracted by the ingest pipeline").inc()
                for i, chunk in enumerate(chunks):
                    self.stats.chunks += 1
                    await out_q.put((chunk, {**item.metadata,
                                             "chunk": i,
                                             "source_id": item.source_id}))
            except Exception as exc:  # noqa: BLE001 — skip bad documents
                self.stats.errors += 1
                obs_metrics.REGISTRY.counter(
                    "ingest_errors_total",
                    "documents the ingest pipeline failed on").inc()
                logger.warning("extract failed for %s: %s",
                               item.source_id or item.path, exc)

    async def _store(self, in_q: asyncio.Queue) -> None:
        loop = asyncio.get_running_loop()
        batch: list[tuple[str, dict]] = []

        async def flush() -> None:
            if not batch:
                return
            texts = [t for t, _ in batch]
            metas = [m for _, m in batch]
            await loop.run_in_executor(
                None, lambda: self.index.add_texts(texts, metas))
            self.stats.chunks_stored += len(batch)
            self.stats.batches += 1
            obs_metrics.REGISTRY.counter(
                "ingest_chunks_total",
                "chunks stored by the ingest pipeline").inc(len(batch))
            batch.clear()

        while True:
            try:
                item = await asyncio.wait_for(in_q.get(),
                                              timeout=self.linger_sec)
            except asyncio.TimeoutError:
                await flush()     # linger expired: don't sit on a batch
                continue
            if item is _STOP:
                await flush()
                return
            batch.append(item)
            if len(batch) >= self.batch_size:
                await flush()

    # ---------------------------------------------------------------- run

    async def run(self) -> PipelineStats:
        raw_q: asyncio.Queue = asyncio.Queue(maxsize=self.queue_size)
        chunk_q: asyncio.Queue = asyncio.Queue(maxsize=self.queue_size)

        async def pump() -> None:
            n = 0
            async for item in self.source:
                await raw_q.put(item)
                self.stats.items_in += 1
                obs_metrics.REGISTRY.counter(
                    "ingest_items_total",
                    "source items entering the ingest pipeline").inc()
                n += 1
                if self.max_items is not None and n >= self.max_items:
                    break
            await raw_q.put(_STOP)

        await asyncio.gather(pump(),
                             self._extract(raw_q, chunk_q),
                             self._store(chunk_q))
        logger.info("ingest finished: %s", self.stats.snapshot())
        return self.stats

    def run_sync(self) -> PipelineStats:
        return asyncio.run(self.run())
