"""On-device token sampling: temperature / top-k / top-p / greedy.

Replaces the sampling config the reference passes as Triton tensors into the
TRT-LLM backend (reference: ensemble_models/llama/ensemble/config.pbtxt:27-117
``top_k``/``top_p``/``temperature``/``random_seed``; client defaults temp 1.0,
top_k 1, top_p 0 in model_server_client/trt_llm.py:68-74).

Everything is batched and static-shape: per-request knobs are vectors, the
"is greedy" decision is a ``where``, and top-k works for any k via a sort +
rank mask (no data-dependent shapes under jit).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30

# Seen/banned vocab masks live packed: 32 tokens per uint32 word (bit i of
# word w covers token w*32+i). A (B, V) bool mask is 1 byte per token in
# HBM; the packed form is 1 bit — 8x less mask traffic every decode step,
# and the fused sampler slices words per vocab tile instead of streaming
# byte-bools for the whole vocabulary.
MASK_BITS = 32


def mask_words(vocab_size: int) -> int:
    """uint32 words needed to cover ``vocab_size`` mask bits."""
    return -(-vocab_size // MASK_BITS)


def pack_mask(mask: jax.Array) -> jax.Array:
    """(…, V) bool -> (…, ceil(V/32)) uint32 bitfield (bit i of word w =
    token w*32+i). Tokens past V pad with 0 (never banned/seen)."""
    V = mask.shape[-1]
    Wn = mask_words(V)
    pad = Wn * MASK_BITS - V
    if pad:
        mask = jnp.pad(mask, [(0, 0)] * (mask.ndim - 1) + [(0, pad)])
    bits = mask.reshape(*mask.shape[:-1], Wn, MASK_BITS).astype(jnp.uint32)
    weights = (jnp.uint32(1) << jnp.arange(MASK_BITS, dtype=jnp.uint32))
    return (bits * weights).sum(-1).astype(jnp.uint32)


def unpack_mask(words: jax.Array, vocab_size: int) -> jax.Array:
    """(…, Wn) uint32 -> (…, vocab_size) bool. ``vocab_size`` may cover a
    slice (e.g. one vocab tile's words with vocab_size = tile)."""
    bits = (words[..., :, None]
            >> jnp.arange(MASK_BITS, dtype=jnp.uint32)) & jnp.uint32(1)
    flat = bits.reshape(*words.shape[:-1], -1)
    return flat[..., :vocab_size].astype(bool)


def pack_mask_np(mask: np.ndarray) -> np.ndarray:
    """numpy twin of pack_mask for host-side mask rendering (the engine
    builds bad-words/prefix-seen masks on the submitting thread)."""
    V = int(mask.shape[-1])
    Wn = mask_words(V)
    padded = np.zeros(mask.shape[:-1] + (Wn * MASK_BITS,), bool)
    padded[..., :V] = mask
    bits = padded.reshape(*mask.shape[:-1], Wn, MASK_BITS)
    weights = (np.uint32(1) << np.arange(MASK_BITS, dtype=np.uint32))
    return (bits.astype(np.uint32) * weights).sum(-1).astype(np.uint32)


def set_token_bits(words: jax.Array, tokens: jax.Array,
                   on: jax.Array) -> jax.Array:
    """Set each row's ``tokens[b]`` bit where ``on[b]`` (rows with
    on=False are untouched). words: (B, Wn) uint32, tokens/on: (B,).
    One word per row is touched, so a gather/modify/scatter is exact."""
    rows = jnp.arange(words.shape[0])
    wi = (tokens // MASK_BITS).astype(jnp.int32)
    bit = (on.astype(jnp.uint32)
           << (tokens % MASK_BITS).astype(jnp.uint32))
    return words.at[rows, wi].set(words[rows, wi] | bit)


def sample(logits: jax.Array, key: jax.Array, temperature: jax.Array,
           top_k: jax.Array, top_p: jax.Array) -> jax.Array:
    """Sample next tokens.

    logits:      (B, V) float
    temperature: (B,) — <= 0 means greedy
    top_k:       (B,) int — <= 0 means unlimited
    top_p:       (B,) float — <= 0 or >= 1 means unlimited
    Returns (B,) int32 token ids.
    """
    B, V = logits.shape
    lf = logits.astype(jnp.float32)
    greedy_ids = jnp.argmax(lf, axis=-1).astype(jnp.int32)

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = lf / temp

    # Rank of each vocab entry (0 = best) via descending sort.
    sort_idx = jnp.argsort(-scaled, axis=-1)                     # (B, V)
    ranks = jnp.zeros_like(sort_idx).at[
        jnp.arange(B)[:, None], sort_idx
    ].set(jnp.broadcast_to(jnp.arange(V), (B, V)))

    k = jnp.where(top_k[:, None] <= 0, V, top_k[:, None])
    keep = ranks < k

    # top-p: keep the smallest prefix of sorted probs with cumsum >= p.
    sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    p = jnp.where((top_p[:, None] <= 0) | (top_p[:, None] >= 1.0),
                  1.0, top_p[:, None])
    # token at sorted position j survives if the cumulative mass *before* it
    # is < p (so the first token always survives).
    sorted_keep_p = (cum - sorted_probs) < p
    keep_p = jnp.zeros_like(keep).at[
        jnp.arange(B)[:, None], sort_idx
    ].set(sorted_keep_p)

    masked = jnp.where(keep & keep_p, scaled, NEG_INF)
    sampled = jax.random.categorical(key, masked, axis=-1).astype(jnp.int32)

    is_greedy = (temperature <= 0) | (top_k == 1)
    return jnp.where(is_greedy, greedy_ids, sampled)


def seen_mask(token_history: jax.Array, valid_len: jax.Array,
              vocab_size: int) -> jax.Array:
    """(B, V) bool mask of tokens present in each row's history.

    token_history: (B, T) int32, valid_len: (B,) valid prefix per row.
    """
    B, T = token_history.shape
    pos_valid = jnp.arange(T)[None, :] < valid_len[:, None]
    return jnp.zeros((B, vocab_size), bool).at[
        jnp.arange(B)[:, None], token_history
    ].max(pos_valid)


def apply_repetition_penalty(logits: jax.Array, seen: jax.Array,
                             penalty: jax.Array) -> jax.Array:
    """CTRL-style repetition penalty over already-seen tokens.

    seen: (B, V) bool (from ``seen_mask`` or maintained incrementally),
    penalty: (B,) — 1.0 is a no-op.
    Parity with the reference's ``repetition_penalty`` ensemble tensor
    (ensemble/config.pbtxt).
    """
    pen = penalty[:, None]
    lf = logits.astype(jnp.float32)
    penalized = jnp.where(lf > 0, lf / pen, lf * pen)
    return jnp.where(seen, penalized, lf).astype(logits.dtype)
