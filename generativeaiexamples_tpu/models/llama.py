"""Llama-family decoder in functional JAX.

The TPU-native replacement for the reference's TRT-LLM engine build
(reference: llm-inference-server/conversion_scripts/llama/build.py) — instead
of building per-rank TensorRT engines, the model is a pure function of a
parameter pytree, jit-compiled by XLA and sharded with NamedSharding.

Design choices (TPU-first, not a port):
- **Stacked layer params + ``lax.scan``**: every per-layer tensor is stacked
  along a leading L axis and the decoder scans over layers. One layer gets
  traced/compiled, not 32/40/80 — compile time stays flat with depth, and
  sharding rules are written once per leaf.
- **Absolute-position KV cache**: cache index == token position. Prefill and
  decode are the same function with different (tokens, positions) shapes; no
  dynamic shapes ever reach XLA.
- **GQA without KV duplication**: grouped einsum in ``ops.attention`` instead
  of materializing duplicated KV heads (the reference duplicates weights when
  tp > n_kv_heads, conversion_scripts/llama/weight.py:150-157).
- **MoE branch** (Mixtral): dense-compute router mixing here; the
  expert-parallel shard_map path lives in ``parallel/``.

Param tree (all projections stored input-major so forward is ``x @ W``):
  embed:       (V, D)
  layers:
    attn_norm: (L, D)         mlp_norm: (L, D)
    wq: (L, D, H*hd)  wk: (L, D, KV*hd)  wv: (L, D, KV*hd)  wo: (L, H*hd, D)
    w_gate/w_up: (L, D, F)    w_down: (L, F, D)          [dense MLP]
    router: (L, D, E)  w_gate/w_up: (L, E, D, F)  w_down: (L, E, F, D)  [MoE]
  final_norm:  (D,)
  lm_head:     (D, V)
"""

from __future__ import annotations

from typing import Any, Optional

import functools
import os

import jax
import jax.numpy as jnp

from ..ops.attention import gqa_attention
from ..ops.quant import matmul as qmm
from ..ops.quant import matmul_f32 as qmm_f32
from ..ops.rmsnorm import layernorm1p, rmsnorm
from ..ops.rope import apply_rope, rope_frequencies
from .configs import LlamaConfig


def use_paged_kernel(cfg: LlamaConfig, page: int) -> bool:
    """Public alias: whether the Pallas paged-attention decode kernel will
    be used for this config (the engine pins pool layouts accordingly)."""
    return _use_paged_kernel(cfg, page)


def _use_paged_kernel(cfg: LlamaConfig, page: int) -> bool:
    """Pallas paged-attention gate: on TPU backends with kernel-supported
    geometry (lane-aligned head_dim/page), unless disabled via
    GENAI_TPU_PAGED_KERNEL=0. Other backends take the jnp gather path."""
    flag = os.environ.get("GENAI_TPU_PAGED_KERNEL", "auto")
    if flag == "0":
        return False
    from ..ops.paged_attention import kernel_supported
    ok = kernel_supported(page, cfg.num_heads, cfg.num_kv_heads,
                          cfg.head_dim)
    if flag == "1":
        return ok
    try:
        return ok and jax.default_backend() == "tpu"
    except Exception:
        return False

Params = dict[str, Any]
KVCache = dict[str, jax.Array]  # {"k": (L,B,T,KV,hd), "v": (L,B,T,KV,hd)}


def init_params(cfg: LlamaConfig, key: jax.Array,
                dtype: jnp.dtype = jnp.bfloat16) -> Params:
    """Random-init parameter tree (for tests/benchmarks; real weights come
    from ``import_hf``)."""
    k = iter(jax.random.split(key, 16))
    D, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    H, KV, hd, V = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim, cfg.vocab_size

    def norm(rng, shape, fan_in):
        return (jax.random.normal(rng, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    # layernorm1p stores weights centered at zero (applied as 1 + w)
    norm_w = jnp.zeros if cfg.norm == "layernorm1p" else jnp.ones
    layers: dict[str, jax.Array] = {
        "attn_norm": norm_w((L, D), dtype),
        "mlp_norm": norm_w((L, D), dtype),
        "wq": norm(next(k), (L, D, H * hd), D),
        "wk": norm(next(k), (L, D, KV * hd), D),
        "wv": norm(next(k), (L, D, KV * hd), D),
        "wo": norm(next(k), (L, H * hd, D), H * hd),
    }
    if cfg.norm == "layernorm1p":
        layers["attn_norm_b"] = jnp.zeros((L, D), dtype)
        layers["mlp_norm_b"] = jnp.zeros((L, D), dtype)
    if cfg.attn_bias:
        layers["bq"] = jnp.zeros((L, H * hd), dtype)
        layers["bk"] = jnp.zeros((L, KV * hd), dtype)
        layers["bv"] = jnp.zeros((L, KV * hd), dtype)
        layers["bo"] = jnp.zeros((L, D), dtype)
    if cfg.num_experts:
        E = cfg.num_experts
        layers.update({
            "router": norm(next(k), (L, D, E), D),
            "w_gate": norm(next(k), (L, E, D, F), D),
            "w_up": norm(next(k), (L, E, D, F), D),
            "w_down": norm(next(k), (L, E, F, D), F),
        })
    elif cfg.mlp == "squared_relu":
        # GPT-Next MLP: no gate projection
        layers.update({
            "w_up": norm(next(k), (L, D, F), D),
            "w_down": norm(next(k), (L, F, D), F),
        })
        if cfg.mlp_bias:
            layers["b_up"] = jnp.zeros((L, F), dtype)
            layers["b_down"] = jnp.zeros((L, D), dtype)
    else:
        layers.update({
            "w_gate": norm(next(k), (L, D, F), D),
            "w_up": norm(next(k), (L, D, F), D),
            "w_down": norm(next(k), (L, F, D), F),
        })
    params: Params = {
        "embed": norm(next(k), (V, D), D),
        "layers": layers,
        "final_norm": norm_w((D,), dtype),
    }
    if cfg.norm == "layernorm1p":
        params["final_norm_b"] = jnp.zeros((D,), dtype)
    if not cfg.tie_word_embeddings:
        params["lm_head"] = norm(next(k), (D, V), D)
    return params


def init_kv_cache(cfg: LlamaConfig, batch: int, max_len: int,
                  dtype: jnp.dtype = jnp.bfloat16) -> KVCache:
    shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def init_paged_kv_cache(cfg: LlamaConfig, n_pages: int, page_size: int,
                        dtype: jnp.dtype = jnp.bfloat16,
                        quantized: bool = False) -> KVCache:
    """Block-pool KV cache: {"k","v"}: (L, n_pages, KV, page, hd).

    The pool is shared by all decode slots through per-slot block tables —
    the XLA-static equivalent of TRT-LLM's paged KV cache
    (reference: ensemble_models/llama/tensorrt_llm/config.pbtxt.j2:28-34).
    Page 0 is reserved as a trash page: writes for inactive slots and
    prefill-bucket overhang are routed there.

    Layout: KV heads ahead of the page dim so a page block arrives in VMEM
    as (KV, page, hd) — exactly the batched-matmul operand shape the Pallas
    decode kernel consumes, with (page, hd) on the tiled lanes and no
    in-kernel transpose.

    ``quantized``: int8 pools + bf16 per-row scale pools ``"ks"/"vs"``
    shaped (L, n_pages, KV, page) — half the HBM bytes per cached token
    (ops/kv_quant.py), the lever toward the reference's batch-128 class
    capacity (reference: config.pbtxt.j2:29).
    """
    shape = (cfg.num_layers, n_pages, cfg.num_kv_heads, page_size,
             cfg.head_dim)
    if not quantized:
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    from ..ops.kv_quant import SCALE_DTYPE
    return {"k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "ks": jnp.zeros(shape[:4], SCALE_DTYPE),
            "vs": jnp.zeros(shape[:4], SCALE_DTYPE)}


def kv_cache_quantized(kv_cache: KVCache) -> bool:
    """Whether a paged pool carries int8 rows + scale leaves."""
    return "ks" in kv_cache


def _gathered_window(pool_layer, scales_layer, block_table, B, P, page,
                     cfg: LlamaConfig, dtype):
    """One layer's slot windows gathered from the paged pool:
    (N, KV, page, hd) -> (B, P*page, KV, hd), dequantizing int8 pages via
    their per-row scales (``scales_layer`` (N, KV, page), or None for a
    full-precision pool). Shared by the decode and chunked-prefill jnp
    paths."""
    g = pool_layer[block_table]                 # (B, P, KV, page, hd)
    if scales_layer is not None:
        from ..ops.kv_quant import dequantize_rows
        g = dequantize_rows(g, scales_layer[block_table], dtype)
    return g.swapaxes(2, 3).reshape(B, P * page, cfg.num_kv_heads,
                                    cfg.head_dim)


def kernel_tp_compatible(cfg: LlamaConfig, mesh) -> bool:
    """Whether the Pallas decode kernel can run under this mesh via
    shard_map: only the tp axis may shard attention state (heads divide
    cleanly); a pp axis would split the pool's layer dim out from under
    the kernel's layer indexing."""
    if mesh is None:
        return True
    tp = mesh.shape.get("tp", 1)
    if mesh.shape.get("pp", 1) != 1:
        return False
    return (cfg.num_kv_heads % tp == 0 and cfg.num_heads % tp == 0
            and (cfg.num_kv_heads // tp) > 0)


def apply_decode_paged(params: Params, cfg: LlamaConfig, tokens: jax.Array,
                       positions: jax.Array, kv_cache: KVCache,
                       block_table: jax.Array, kv_valid_len: jax.Array,
                       write_page: jax.Array, write_offset: jax.Array,
                       use_kernel: Optional[bool] = None,
                       mesh=None, return_hidden: bool = False,
                       ) -> tuple[jax.Array, KVCache]:
    """Single-token decode step over the paged KV pool.

    tokens/positions: (B, 1). block_table: (B, P) — physical page id of each
    slot's logical page, sliced by the engine to the smallest window covering
    every active sequence (so HBM reads scale with actual context, not cache
    capacity). write_page/write_offset: (B,) physical destination of this
    step's K/V (page 0 = trash for inactive slots). Returns
    (logits (B, 1, V), updated cache) — or (hidden (B, 1, D), cache)
    under ``return_hidden`` (the engine's fused vocab-tiled sampling
    tail does its own norm + streamed projection; see
    ops/fused_sampler.py).

    Memory discipline: the layer scan only READS the pool; each layer's new
    K/V (tiny) is collected as a scan output and the pool is updated with
    ONE in-place scatter afterwards. Routing the pool itself through the
    scan as sliced-xs/stacked-ys would make XLA materialize a second full
    copy of the pool as loop temporaries — 2x pool HBM, the round-2 bench
    OOM. The current token instead rides the gathered attention window.
    """
    B, S = tokens.shape
    P = block_table.shape[1]
    page = kv_cache["k"].shape[3]  # (L, N, KV, page, hd)
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta,
                                cfg.rope_scaling_factor)
    h = jnp.take(params["embed"], tokens, axis=0)
    pos_in_win = positions[:, 0]  # logical index of the current token
    rows = jnp.arange(B)

    # use_kernel: the caller (engine) decides — the Pallas path has no
    # SPMD partitioning rule, so mesh/TP serving must take the jnp path.
    # None = auto for single-device callers.
    if use_kernel is None:
        use_kernel = _use_paged_kernel(cfg, page)
    quant = kv_cache_quantized(kv_cache)
    if use_kernel:
        # Kernel path: the pools ride the scan CARRY and pass through the
        # Pallas call aliased in place (attention read + row append happen
        # inside the kernel). No XLA gather/scatter ever touches the pool,
        # so no layout fights and no carry double-buffering.
        from ..ops.paged_attention import paged_attention_decode
        # int8-KV pools: the kernel quantizes the appended row itself, so
        # the current token's K/V pass in compute dtype, not pool dtype.
        dt = h.dtype if quant else kv_cache["k"].dtype
        # Pallas has no SPMD partitioning rule, so under a tp mesh the
        # call is shard_mapped: each device runs the kernel on its own
        # H/tp query heads and KV/tp pool shard — table/positions are
        # replicated, and the append lands in the local shard. This is
        # what keeps the v5e-8 TP serving config off the ~10x-slower
        # gather path (VERDICT r3 weak #3).
        interp = jax.default_backend() != "tpu"

        if quant:
            def call_kernel(q, pk, pv, ks, vs, ck, cv, li, tbl, lens,
                            wp, off):
                return paged_attention_decode(
                    q, pk, pv, tbl, lens, ck, cv, wp, off, li,
                    pool_ks=ks, pool_vs=vs, interpret=interp)
        else:
            def call_kernel(q, pk, pv, ck, cv, li, tbl, lens, wp, off):
                return paged_attention_decode(
                    q, pk, pv, tbl, lens, ck, cv, wp, off, li,
                    interpret=interp)

        if mesh is not None and "tp" in mesh.shape:
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P
            kv_spec = P(None, None, "tp", None, None)
            sc_spec = P(None, None, "tp", None)
            head_specs = (P(None, "tp", None),) * 2  # ck, cv
            if quant:
                in_specs = ((P(None, "tp", None), kv_spec, kv_spec,
                             sc_spec, sc_spec) + head_specs
                            + (P(), P(), P(), P(), P()))
                out_specs = (P(None, "tp", None), kv_spec, kv_spec,
                             sc_spec, sc_spec)
            else:
                in_specs = ((P(None, "tp", None), kv_spec, kv_spec)
                            + head_specs + (P(), P(), P(), P(), P()))
                out_specs = (P(None, "tp", None), kv_spec, kv_spec)
            call_kernel = shard_map(
                call_kernel, mesh=mesh, in_specs=in_specs,
                out_specs=out_specs, check_rep=False)

        def layer_k(carry, lp):
            if quant:
                h, pk, pv, ks, vs, li = carry
            else:
                h, pk, pv, li = carry

            def attend(q, k, v):
                if quant:
                    attn, pk2, pv2, ks2, vs2 = call_kernel(
                        q[:, 0], pk, pv, ks, vs, k[:, 0].astype(dt),
                        v[:, 0].astype(dt), li, block_table, pos_in_win,
                        write_page, write_offset)
                    return attn[:, None], (pk2, pv2, ks2, vs2)
                attn, pk2, pv2 = call_kernel(
                    q[:, 0], pk, pv, k[:, 0].astype(dt),
                    v[:, 0].astype(dt), li, block_table, pos_in_win,
                    write_page, write_offset)
                return attn[:, None], (pk2, pv2)

            if quant:
                h, (pk, pv, ks, vs) = decoder_layer(
                    h, lp, cfg, positions, inv_freq, kv_valid_len,
                    attend=attend)
                return (h, pk, pv, ks, vs, li + 1), None
            h, (pk, pv) = decoder_layer(h, lp, cfg, positions, inv_freq,
                                        kv_valid_len, attend=attend)
            return (h, pk, pv, li + 1), None

        li0 = jnp.zeros((1,), jnp.int32)
        if quant:
            (h, pk, pv, ks, vs, _), _ = jax.lax.scan(
                layer_k, (h, kv_cache["k"], kv_cache["v"],
                          kv_cache["ks"], kv_cache["vs"], li0),
                params["layers"])
            out = h if return_hidden else unembed(params, cfg, h)
            return out, {"k": pk, "v": pv, "ks": ks, "vs": vs}
        (h, pk, pv, _), _ = jax.lax.scan(
            layer_k, (h, kv_cache["k"], kv_cache["v"], li0),
            params["layers"])
        return (h if return_hidden else unembed(params, cfg, h)), \
            {"k": pk, "v": pv}

    def layer(h: jax.Array, xs):
        if quant:
            lp, kc, vc, ksc, vsc = xs
        else:
            lp, kc, vc = xs
            ksc = vsc = None

        def attend(q, k, v):
            kg = _gathered_window(kc, ksc, block_table, B, P, page, cfg,
                                  h.dtype)
            vg = _gathered_window(vc, vsc, block_table, B, P, page, cfg,
                                  h.dtype)
            # Current token joins the window in-register (its pool
            # write happens in the post-scan scatter).
            kg = kg.at[rows, pos_in_win].set(k[:, 0].astype(kg.dtype))
            vg = vg.at[rows, pos_in_win].set(v[:, 0].astype(vg.dtype))
            return gqa_attention(q, kg, vg, positions, kv_valid_len), \
                (k[:, 0], v[:, 0])

        return decoder_layer(h, lp, cfg, positions, inv_freq, kv_valid_len,
                             attend=attend)

    xs = (params["layers"], kv_cache["k"], kv_cache["v"])
    if quant:
        xs = xs + (kv_cache["ks"], kv_cache["vs"])
    h, (new_k, new_v) = jax.lax.scan(layer, h, xs)
    # new_k/new_v: (L, B, KV, hd) -> one scatter into the (donated) pool.
    # Flattening (N, KV, page) into one dim keeps the scatter single-axis
    # and layout-neutral.
    L_, N_, KV_, page_, hd_ = kv_cache["k"].shape
    flat_idx = ((write_page[:, None] * KV_ + jnp.arange(KV_)[None, :])
                * page_ + write_offset[:, None])               # (B, KV)

    def write(pool, new):
        flat = pool.reshape(L_, N_ * KV_ * page_, hd_)
        flat = flat.at[:, flat_idx].set(new.astype(pool.dtype))
        return flat.reshape(L_, N_, KV_, page_, hd_)

    if quant:
        from ..ops.kv_quant import quantize_rows

        def write_scale(pool, new_s):
            flat = pool.reshape(L_, N_ * KV_ * page_)
            flat = flat.at[:, flat_idx].set(new_s.astype(pool.dtype))
            return flat.reshape(L_, N_, KV_, page_)

        kq, ksn = quantize_rows(new_k)
        vq, vsn = quantize_rows(new_v)
        cache = {"k": write(kv_cache["k"], kq),
                 "v": write(kv_cache["v"], vq),
                 "ks": write_scale(kv_cache["ks"], ksn),
                 "vs": write_scale(kv_cache["vs"], vsn)}
    else:
        cache = {"k": write(kv_cache["k"], new_k),
                 "v": write(kv_cache["v"], new_v)}
    return (h if return_hidden else unembed(params, cfg, h)), cache


def apply_verify_paged(params: Params, cfg: LlamaConfig, tokens: jax.Array,
                       positions: jax.Array, kv_cache: KVCache,
                       block_table: jax.Array, kv_valid_len: jax.Array,
                       write_pages: jax.Array, write_offsets: jax.Array,
                       return_hidden: bool = False,
                       ) -> tuple[jax.Array, KVCache]:
    """Multi-token decode step over the paged KV pool: the speculative-
    decoding VERIFICATION forward (engine/spec_decode.py).

    Scores ``S`` consecutive positions per slot in ONE forward — the
    last accepted token plus up to S-1 draft tokens — so the engine can
    emit several tokens per model step.  tokens/positions: (B, S) with
    each row's positions contiguous (``pos .. pos+S-1``).
    write_pages/write_offsets: (B, S) physical destination of EACH
    token's K/V (page 0 = trash for inactive slots and positions past
    the slot's draft count).  kv_valid_len: (B,) = ``pos + S`` — the
    causal mask inside :func:`gqa_attention` restricts each query to
    keys at positions <= its own, so draft token j attends the pool
    prefix plus drafts 0..j-1 exactly as a sequential decode would.

    Rollback discipline: rejected drafts need NO explicit undo.  Their
    K/V rows land at positions past the last accepted token; the engine
    simply does not advance ``pos`` past acceptance, so the next step's
    writes overwrite them and reads (masked by ``pos``) never see them
    — pages never advance past the last accepted token and prefix-cache
    block hashes (pure prompt blocks) stay consistent.

    This is the jnp gather path only — the mirror of
    ``apply_decode_paged``'s fallback branch generalized to S tokens.
    The Pallas decode kernel stays single-token (its per-slot DMA loop
    is shaped around one query row); verify rounds take this path on
    every backend, trading a gathered window per layer for the K+1
    scoring positions.  Same memory discipline: the layer scan only
    READS the pool, each layer's new K/V rides the scan outputs, and
    the pool is updated with one post-scan scatter.
    """
    B, S = tokens.shape
    P = block_table.shape[1]
    page = kv_cache["k"].shape[3]  # (L, N, KV, page, hd)
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta,
                                cfg.rope_scaling_factor)
    h = jnp.take(params["embed"], tokens, axis=0)
    rows = jnp.arange(B)
    quant = kv_cache_quantized(kv_cache)

    def layer(h: jax.Array, xs):
        if quant:
            lp, kc, vc, ksc, vsc = xs
        else:
            lp, kc, vc = xs
            ksc = vsc = None

        def attend(q, k, v):
            kg = _gathered_window(kc, ksc, block_table, B, P, page, cfg,
                                  h.dtype)
            vg = _gathered_window(vc, vsc, block_table, B, P, page, cfg,
                                  h.dtype)
            # All S current tokens join the window in-register at their
            # logical positions (their pool writes happen in the
            # post-scan scatter); positions past the window drop on
            # scatter — they can only belong to masked garbage rows.
            kg = kg.at[rows[:, None], positions].set(k.astype(kg.dtype))
            vg = vg.at[rows[:, None], positions].set(v.astype(vg.dtype))
            return gqa_attention(q, kg, vg, positions, kv_valid_len), \
                (k, v)

        return decoder_layer(h, lp, cfg, positions, inv_freq, kv_valid_len,
                             attend=attend)

    xs = (params["layers"], kv_cache["k"], kv_cache["v"])
    if quant:
        xs = xs + (kv_cache["ks"], kv_cache["vs"])
    h, (new_k, new_v) = jax.lax.scan(layer, h, xs)
    # new_k/new_v: (L, B, S, KV, hd) -> one scatter into the pool, one
    # flat row index per (slot, token, kv-head).
    L_, N_, KV_, page_, hd_ = kv_cache["k"].shape
    flat_idx = ((write_pages[:, :, None] * KV_
                 + jnp.arange(KV_)[None, None, :])
                * page_ + write_offsets[:, :, None])       # (B, S, KV)

    def write(pool, new):
        flat = pool.reshape(L_, N_ * KV_ * page_, hd_)
        flat = flat.at[:, flat_idx].set(new.astype(pool.dtype))
        return flat.reshape(L_, N_, KV_, page_, hd_)

    if quant:
        from ..ops.kv_quant import quantize_rows

        def write_scale(pool, new_s):
            flat = pool.reshape(L_, N_ * KV_ * page_)
            flat = flat.at[:, flat_idx].set(new_s.astype(pool.dtype))
            return flat.reshape(L_, N_, KV_, page_)

        kq, ksn = quantize_rows(new_k)
        vq, vsn = quantize_rows(new_v)
        cache = {"k": write(kv_cache["k"], kq),
                 "v": write(kv_cache["v"], vq),
                 "ks": write_scale(kv_cache["ks"], ksn),
                 "vs": write_scale(kv_cache["vs"], vsn)}
    else:
        cache = {"k": write(kv_cache["k"], new_k),
                 "v": write(kv_cache["v"], new_v)}
    return (h if return_hidden else unembed(params, cfg, h)), cache


def _paged_prefix_attention(q, k_self, v_self, kc, vc, ksc, vsc,
                            block_table, start, kv_valid_len, page: int,
                            cfg: LlamaConfig, block_pages: int = 8):
    """Chunk queries attend [pooled prefix] + [their own chunk], with the
    prefix STREAMED from the pool in ``block_pages``-page blocks under an
    online softmax.

    The former implementation gathered the whole window up front —
    (1, P*page, KV, hd) per layer, ~4 GB per tensor at 16k tokens on 7B —
    which capped chunked long-prompt serving far below the pool's own
    capacity. Block streaming bounds the transient to one block's K/V
    plus one (KV, G, C, block) score tile, independent of prefix length.

    q:            (1, C, H, hd) post-rope queries (C = chunk length)
    k/v_self:     (1, C, KV, hd) this chunk's post-rope K/V (NOT yet in
                  the pool — the pool's rows for these positions are
                  stale, so the self part computes in-register)
    kc/vc:        (N, KV, page, hd) one layer's pool (int8 when ksc/vsc
                  per-row scale layers are given)
    block_table:  (1, P) logical→physical window
    start:        () int32 — absolute position of the chunk's first row
                  (page-aligned); pool rows with logical position >=
                  start are masked (stale/future)
    kv_valid_len: (1,) int32 — start + valid tokens in this chunk
    Returns (1, C, H, hd) in q.dtype.
    """
    B, C, H, hd = q.shape
    KV = cfg.num_kv_heads
    G = H // KV
    scale = 1.0 / (hd ** 0.5)
    P = block_table.shape[1]
    nb = -(-P // block_pages)
    tbl = jnp.pad(block_table[0], (0, nb * block_pages - P))
    cd = q.dtype
    # operands stay in storage dtype into the MXU with f32 accumulation
    # (casting whole K/V blocks to f32 up front would double the
    # prefix stream's HBM bytes — the anti-pattern ops/attention.py's
    # chunked path documents avoiding); softmax state is f32.
    qf = q[0].reshape(C, KV, G, hd)
    tblk = block_pages * page
    rel = jnp.arange(C, dtype=jnp.int32)

    def online(carry, s, mask, vb):
        """One online-softmax update. s: (KV, G, C, T) f32 scores,
        mask (C, T) or (T,); explicit zeroing of masked probabilities —
        relying on exp(-1e30 - m) underflow alone breaks the moment a
        stale pool row is non-finite (NaN * 0 = NaN)."""
        m, l, acc = carry
        mb = jnp.broadcast_to(mask, s.shape[-2:])[None, None]
        s = jnp.where(mb, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.where(mb, jnp.exp(s - m_new[..., None]), 0.0)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = (acc * alpha[..., None]
                   + jnp.einsum("kgct,tkh->kgch", p.astype(cd), vb,
                                preferred_element_type=jnp.float32))
        return m_new, l_new, acc_new

    def dequant_block(pool, scales, pages):
        g = pool[pages]                         # (bp, KV, page, hd)
        if scales is not None:
            from ..ops.kv_quant import dequantize_rows
            g = dequantize_rows(g, scales[pages], cd)
        return g.swapaxes(1, 2).reshape(tblk, KV, hd).astype(cd)

    def block(carry, bi):
        def live(carry):
            pages = jax.lax.dynamic_slice(tbl, (bi * block_pages,),
                                          (block_pages,))
            kb = dequant_block(kc, ksc, pages)
            vb = dequant_block(vc, vsc, pages)
            t = bi * tblk + jnp.arange(tblk, dtype=jnp.int32)
            s = jnp.einsum("ckgh,tkh->kgct", qf, kb,
                           preferred_element_type=jnp.float32) * scale
            # prefix rows only: pool rows at/past `start` are stale
            # (this chunk's own rows land post-scan) — and every prefix
            # row is causally visible to every chunk query (t < start)
            return online(carry, s, t < start, vb)
        # blocks wholly past the prefix would be gathered then fully
        # masked — skip their HBM reads and matmuls at runtime
        return jax.lax.cond(bi * tblk < start, live,
                            lambda c: c, carry), None

    m0 = jnp.full((KV, G, C), -1e30, jnp.float32)
    l0 = jnp.zeros((KV, G, C), jnp.float32)
    acc0 = jnp.zeros((KV, G, C, hd), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(
        block, (m0, l0, acc0), jnp.arange(nb, dtype=jnp.int32))

    # the chunk itself, ALSO in key blocks — a dense (KV, G, C, C) f32
    # score tensor at C=2048 on 7B is 512 MB/layer, the transient the
    # chunked-attention machinery exists to avoid
    sb = min(C, 512)
    while C % sb:
        sb //= 2
    ks, vs = k_self[0], v_self[0]               # (C, KV, hd)

    def self_block(carry, si):
        kb = jax.lax.dynamic_slice(ks, (si * sb, 0, 0), (sb, KV, hd))
        vb = jax.lax.dynamic_slice(vs, (si * sb, 0, 0), (sb, KV, hd))
        tloc = si * sb + jnp.arange(sb, dtype=jnp.int32)
        s = jnp.einsum("ckgh,tkh->kgct", qf, kb,
                       preferred_element_type=jnp.float32) * scale
        ok = (tloc[None, :] <= rel[:, None]) \
            & ((start + tloc) < kv_valid_len[0])[None, :]
        return online(carry, s, ok, vb), None

    (m, l, acc), _ = jax.lax.scan(
        self_block, (m, l, acc), jnp.arange(C // sb, dtype=jnp.int32))
    # valid queries attend at least themselves (l > 0); PADDED rows past
    # kv_valid_len attend nothing — floor the denominator so they yield
    # zeros, not NaNs that would trip debug tooling downstream
    out = acc / jnp.maximum(l[..., None], 1e-30)
    # (KV, G, C, hd) -> (1, C, H, hd)
    return out.transpose(2, 0, 1, 3).reshape(1, C, H, hd).astype(q.dtype)


def apply_prefill_paged(params: Params, cfg: LlamaConfig, tokens: jax.Array,
                        positions: jax.Array, kv_cache: KVCache,
                        block_table: jax.Array, kv_valid_len: jax.Array,
                        start_page_idx: jax.Array, *,
                        with_logits: bool = False,
                        ) -> tuple[jax.Array, KVCache]:
    """One CHUNK of a long-prompt prefill over the paged KV pool (B=1).

    The piece that lets the engine serve prompts longer than any single
    prefill bucket: the prompt streams through in page-aligned chunks,
    each chunk's KV lands in the slot's pool pages, and its attention
    reads the whole prefix back from the pool — exact attention, bounded
    activation memory (one chunk's worth).

    tokens/positions: (1, C), C a page multiple, positions starting at a
    page boundary. block_table: (1, P) logical→physical window covering
    at least ``kv_valid_len`` tokens. kv_valid_len: (1,) = chunk start +
    valid tokens in this chunk (padding rows beyond it are causally
    masked AND their pool rows are later overwritten or never read).
    start_page_idx: () int32 — logical page index of the chunk's first
    row; destination pages are ``block_table[0, start_page_idx + i]``.
    Returns (hidden states (1, C, D), updated pool) by default — the
    engine unembeds only the sampling position; ``with_logits=True``
    returns full (1, C, V) logits instead (a large transient at big
    vocab x chunk; only for callers that truly need every position).

    Same memory discipline as the decode path's jnp branch: the layer
    scan only READS the pool; per-layer chunk KV is collected as stacked
    scan outputs and scattered into the pages once, after the scan — the
    chunk rides the gathered window in-register for its own attention.
    """
    B, C = tokens.shape
    if B != 1:
        raise ValueError("apply_prefill_paged is single-request (B=1)")
    P = block_table.shape[1]
    page = kv_cache["k"].shape[3]  # (L, N, KV, page, hd)
    if C % page:
        raise ValueError(f"chunk {C} not a page ({page}) multiple")
    nb = C // page
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta,
                                cfg.rope_scaling_factor)
    h = jnp.take(params["embed"], tokens, axis=0)
    start = positions[0, 0]  # absolute position of the chunk's first row

    quant = kv_cache_quantized(kv_cache)

    def layer(h: jax.Array, xs):
        if quant:
            lp, kc, vc, ksc, vsc = xs
        else:
            lp, kc, vc = xs
            ksc = vsc = None

        def attend(q, k, v):
            # prefix streamed from the pool block-by-block (online
            # softmax) + the chunk's own K/V in-register; the pool write
            # happens in the one post-scan scatter. Never materializes
            # the full gathered window — prefix length does not bound
            # this path's memory.
            attn = _paged_prefix_attention(
                q, k, v, kc, vc, ksc, vsc, block_table, start,
                kv_valid_len, page, cfg)
            return attn, (k[0], v[0])

        return decoder_layer(h, lp, cfg, positions, inv_freq, kv_valid_len,
                             attend=attend)

    xs = (params["layers"], kv_cache["k"], kv_cache["v"])
    if quant:
        xs = xs + (kv_cache["ks"], kv_cache["vs"])
    h, (new_k, new_v) = jax.lax.scan(layer, h, xs)
    # new_k/new_v: (L, C, KV, hd) -> (L, nb, KV, page, hd) page blocks,
    # scattered at the chunk's physical pages in one shot.
    L_ = new_k.shape[0]
    dest = jax.lax.dynamic_slice(block_table[0], (start_page_idx,), (nb,))

    def write(pool, new):
        blocks = new.reshape(L_, nb, page, cfg.num_kv_heads,
                             cfg.head_dim).swapaxes(2, 3)
        return pool.at[:, dest].set(blocks.astype(pool.dtype))

    if quant:
        from ..ops.kv_quant import quantize_rows
        kq, ksn = quantize_rows(new_k)           # scales: (L, C, KV)
        vq, vsn = quantize_rows(new_v)

        def write_scale(pool, new_s):
            blocks = new_s.reshape(L_, nb, page,
                                   cfg.num_kv_heads).swapaxes(2, 3)
            return pool.at[:, dest].set(blocks.astype(pool.dtype))

        cache = {"k": write(kv_cache["k"], kq),
                 "v": write(kv_cache["v"], vq),
                 "ks": write_scale(kv_cache["ks"], ksn),
                 "vs": write_scale(kv_cache["vs"], vsn)}
    else:
        cache = {"k": write(kv_cache["k"], new_k),
                 "v": write(kv_cache["v"], new_v)}
    if not with_logits:
        return h, cache
    return unembed(params, cfg, h), cache


def _dense_mlp(x: jax.Array, lp: dict[str, jax.Array],
               cfg: LlamaConfig) -> jax.Array:
    if cfg.mlp == "squared_relu":
        # GPT-Next: relu(x W_up)^2 W_down — non-gated
        up = qmm(x, lp["w_up"])
        if "b_up" in lp:
            up = up + lp["b_up"]
        act = jnp.square(jax.nn.relu(up))
        out = qmm(act, lp["w_down"])
        if "b_down" in lp:
            out = out + lp["b_down"]
        return out
    gate = jax.nn.silu(qmm(x, lp["w_gate"]))
    return qmm(gate * qmm(x, lp["w_up"]), lp["w_down"])


def block_norm(x: jax.Array, lp: dict[str, jax.Array], key: str,
               cfg: LlamaConfig) -> jax.Array:
    """The per-block normalization — rmsnorm (llama) or layernorm1p
    (GPT-Next), selected by config."""
    if cfg.norm == "layernorm1p":
        return layernorm1p(x, lp[key], lp[key + "_b"], cfg.rms_norm_eps)
    return rmsnorm(x, lp[key], cfg.rms_norm_eps)


def _moe_mlp(x: jax.Array, lp: dict[str, jax.Array], cfg: LlamaConfig) -> jax.Array:
    """Mixtral MLP. Default is the sparse top-k capacity-routed path
    (parallel/moe.py, O(tokens*k) expert FLOPs); ``moe_impl="dense"``
    keeps the zero-gated all-experts formulation (O(tokens*E), no
    capacity drops) as the parity oracle."""
    if cfg.moe_impl == "sparse":
        from ..parallel.moe import sparse_moe_ffn
        return sparse_moe_ffn(x, lp, cfg)
    if cfg.moe_impl != "dense":
        raise ValueError(f"unknown moe_impl {cfg.moe_impl!r}; "
                         f"expected 'sparse' or 'dense'")
    B, S, D = x.shape
    logits = x @ lp["router"]  # (B,S,E)
    weights, idx = jax.lax.top_k(logits, cfg.num_experts_per_tok)
    weights = jax.nn.softmax(weights.astype(jnp.float32), axis=-1).astype(x.dtype)
    # gates: (B,S,E) with softmaxed weights at the top-k positions
    gates = jnp.zeros_like(logits).at[
        jnp.arange(B)[:, None, None], jnp.arange(S)[None, :, None], idx
    ].set(weights)
    gate = jax.nn.silu(jnp.einsum("bsd,edf->bsef", x, lp["w_gate"]))
    up = jnp.einsum("bsd,edf->bsef", x, lp["w_up"])
    down = jnp.einsum("bsef,efd->bsed", gate * up, lp["w_down"])
    return jnp.einsum("bsed,bse->bsd", down, gates)


def decoder_layer(h: jax.Array, lp: dict[str, jax.Array], cfg: LlamaConfig,
                  positions: jax.Array, inv_freq: jax.Array,
                  kv_valid_len: Optional[jax.Array],
                  cache_kv: Optional[tuple[jax.Array, jax.Array]] = None,
                  row_start: Optional[jax.Array] = None,
                  attend=None):
    """One transformer block. The single source of layer math shared by the
    full forward (``apply``), the paged decode (``apply_decode_paged``
    supplies a paged ``attend``), and the pipeline-parallel stage loop
    (``parallel/pipeline.py``).

    cache_kv: optional (kc, vc) of shape (B, T, KV, hd); new K/V written at
    ``row_start + offset`` per row. ``attend(q, k, v) -> (attn, new_cache)``
    overrides the whole KV-write + attention step (used by the paged
    decode). Returns (h, new_cache_or_None).
    """
    B, S, _ = h.shape
    x = block_norm(h, lp, "attn_norm", cfg)
    q = qmm(x, lp["wq"])
    k = qmm(x, lp["wk"])
    v = qmm(x, lp["wv"])
    if "bq" in lp:
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    q = q.reshape(B, S, cfg.num_heads, cfg.head_dim)
    k = k.reshape(B, S, cfg.num_kv_heads, cfg.head_dim)
    v = v.reshape(B, S, cfg.num_kv_heads, cfg.head_dim)
    q, k = apply_rope(q, k, positions, inv_freq)
    if attend is not None:
        attn, new_cache = attend(q, k, v)
    elif cache_kv is not None:
        kc, vc = cache_kv
        # Write this chunk at its absolute positions (rows contiguous).
        kc = jax.vmap(
            lambda c, u, s: jax.lax.dynamic_update_slice(c, u, (s, 0, 0))
        )(kc, k, row_start)
        vc = jax.vmap(
            lambda c, u, s: jax.lax.dynamic_update_slice(c, u, (s, 0, 0))
        )(vc, v, row_start)
        attn = gqa_attention(q, kc, vc, positions, kv_valid_len)
        new_cache = (kc, vc)
    else:
        attn = gqa_attention(q, k, v, positions, kv_valid_len)
        new_cache = None
    attn_out = qmm(attn.reshape(B, S, cfg.q_dim), lp["wo"])
    if "bo" in lp:
        attn_out = attn_out + lp["bo"]
    h = h + attn_out
    x = block_norm(h, lp, "mlp_norm", cfg)
    mlp = _moe_mlp(x, lp, cfg) if cfg.num_experts else _dense_mlp(x, lp, cfg)
    return h + mlp, new_cache


def run_layers(layers: dict[str, jax.Array], cfg: LlamaConfig, h: jax.Array,
               positions: jax.Array,
               kv_valid_len: Optional[jax.Array] = None) -> jax.Array:
    """Scan a (possibly partial) stacked layer stack over hidden states,
    no KV cache — the per-stage body for pipeline parallelism."""
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta,
                                cfg.rope_scaling_factor)

    def body(h, lp):
        h, _ = decoder_layer(h, lp, cfg, positions, inv_freq, kv_valid_len)
        return h, None

    h, _ = jax.lax.scan(body, h, layers)
    return h


def unembed_norm(params: Params, cfg: LlamaConfig, h: jax.Array
                 ) -> jax.Array:
    """The final-norm half of ``unembed`` — the fused vocab-tiled sampler
    (ops/fused_sampler.py) applies it once and then streams the vocab
    projection itself via ``lm_head_tile``."""
    if cfg.norm == "layernorm1p":
        return layernorm1p(h, params["final_norm"], params["final_norm_b"],
                           cfg.rms_norm_eps)
    return rmsnorm(h, params["final_norm"], cfg.rms_norm_eps)


# lm_head QTensor leaves sliced along the vocab (output) axis; K-axis
# leaves (pre_scale) pass through whole.
_HEAD_VOCAB_LEAVES = ("q", "q4", "scale", "gscale", "gbias")


def lm_head_subtree(params: Params) -> dict:
    """The unembed-weight leaves as a standalone mini-tree — the
    shard_map operand of the tp-sharded fused sampler
    (ops/fused_sampler.py ``fused_unembed_sample_tp``). Keeps the
    ``lm_head``/``embed`` key so :func:`lm_head_tile` works on the
    LOCAL shard unchanged inside the shard_map body."""
    head = params.get("lm_head")
    if head is None:
        return {"embed": params["embed"]}
    return {"lm_head": head}


def lm_head_specs(params: Params, mesh, axis: str = "tp") -> dict:
    """PartitionSpecs for :func:`lm_head_subtree`, mirroring
    ``parallel.sharding``'s placement rules (vocab axis over ``tp``;
    quantized dicts follow ``shard_params``' per-leaf derivation) — the
    ``in_specs`` of the sharded fused-sampler tail."""
    from jax.sharding import PartitionSpec as P
    tp = axis if int(mesh.shape.get(axis, 1)) > 1 else None
    head = params.get("lm_head")
    # Tied embedding (V, D): vocab is the LEADING axis.
    if head is None:
        return {"embed": P(tp, None)}

    def leaf(k):
        # mirrors shard_params' QTensor rules for w_spec = (None, tp):
        # vocab-axis leaves keep it; the (V,) scale drops the reduction
        # axis; pre_scale (D,) stays replicated.
        if k in ("q", "q4", "gscale", "gbias"):
            return P(None, tp)
        if k == "pre_scale":
            return P(None)
        return P(tp)
    if isinstance(head, dict):
        return {"lm_head": {k: leaf(k) for k in head}}
    return {"lm_head": P(None, tp)}


def lm_head_tile(params: Params, cfg: LlamaConfig, hn: jax.Array,
                 t0: jax.Array, tile: int) -> jax.Array:
    """Project already-normed hidden states onto ONE vocab tile:
    (B, D) x head[:, t0:t0+tile] -> (B, tile) f32.

    Works for every lm_head storage the repo serves — tied embedding
    (V, D), raw (D, V), and quantized dicts (int8/int4/grouped, whose
    packing runs along the reduction axis, so an output-axis slice stays
    a valid QTensor for ops.quant.matmul_f32). Inside a tile scan the
    slice reads each weight byte exactly once per full vocab pass — the
    same HBM traffic as one materialized unembed, with no (B, V) output."""
    head = params.get("lm_head")
    if head is None:
        e = jax.lax.dynamic_slice_in_dim(params["embed"], t0, tile, axis=0)
        return jax.lax.dot_general(
            hn, e, (((hn.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    if isinstance(head, dict):
        sliced = {k: (jax.lax.dynamic_slice_in_dim(v, t0, tile, axis=-1)
                      if k in _HEAD_VOCAB_LEAVES else v)
                  for k, v in head.items()}
        return qmm_f32(hn, sliced)
    return qmm_f32(hn, jax.lax.dynamic_slice_in_dim(head, t0, tile,
                                                    axis=-1))


def unembed(params: Params, cfg: LlamaConfig, h: jax.Array) -> jax.Array:
    """Final norm + output projection: (B, S, D) -> (B, S, V) float32.

    Operands stay compact (bf16/int8) with f32 MXU accumulation — casting
    to f32 first made XLA materialize an f32 copy of the whole vocab
    projection every decode step (ops/quant.py matmul_f32)."""
    h = unembed_norm(params, cfg, h)
    head = params.get("lm_head")
    if head is None:
        return jax.lax.dot_general(
            h, params["embed"], (((h.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    return qmm_f32(h, head)


def apply(params: Params, cfg: LlamaConfig, tokens: jax.Array,
          positions: jax.Array, kv_cache: Optional[KVCache] = None,
          kv_valid_len: Optional[jax.Array] = None, *,
          return_hidden: bool = False,
          ) -> tuple[jax.Array, Optional[KVCache]]:
    """Forward pass. Serves prefill, decode, and training with one function.

    tokens:      (B, S) int32
    positions:   (B, S) int32 absolute positions (row-contiguous).
    kv_cache:    absolute-position cache; new K/V are written at
                 ``positions`` and attention reads the whole cache.
    kv_valid_len:(B,) valid key count per row. Defaults to
                 ``positions[:, -1] + 1`` when a cache is used, else in-seq
                 causal masking only.
    Returns (logits (B,S,V) or hidden (B,S,D), updated cache or None).
    """
    h = jnp.take(params["embed"], tokens, axis=0)
    row_start = positions[:, 0]
    if kv_cache is not None and kv_valid_len is None:
        kv_valid_len = positions[:, -1] + 1

    if kv_cache is not None:
        inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta,
                                    cfg.rope_scaling_factor)

        def layer_cached(h, xs):
            lp, kc, vc = xs  # kc/vc: (B,T,KV,hd)
            h, new_kv = decoder_layer(h, lp, cfg, positions, inv_freq,
                                      kv_valid_len, (kc, vc), row_start)
            return h, new_kv

        h, (new_k, new_v) = jax.lax.scan(
            layer_cached, h, (params["layers"], kv_cache["k"], kv_cache["v"]))
        new_cache: Optional[KVCache] = {"k": new_k, "v": new_v}
    else:
        h = run_layers(params["layers"], cfg, h, positions, kv_valid_len)
        new_cache = None

    if return_hidden:
        if cfg.norm == "layernorm1p":
            return layernorm1p(h, params["final_norm"],
                               params["final_norm_b"],
                               cfg.rms_norm_eps), new_cache
        return rmsnorm(h, params["final_norm"], cfg.rms_norm_eps), new_cache
    return unembed(params, cfg, h), new_cache


def apply_sp(params: Params, cfg: LlamaConfig, tokens: jax.Array,
             positions: jax.Array, mesh) -> jax.Array:
    """Sequence-parallel long-context forward (ring attention).

    Activations are sharded along the sequence axis over the mesh's ``sp``
    axis — per-device activation memory shrinks by ``sp``, which is what
    lets a prefill far beyond one chip's HBM run at all. Attention is
    exact: KV blocks rotate around the ``sp`` ring with ``ppermute``
    (one ICI hop per step, overlapped with compute) and combine via
    online softmax (parallel/ring_attention.py). Everything else in the
    layer — norms, projections, MLP — is per-token, so sequence sharding
    passes through it untouched. Params are replicated across ``sp``
    (and sharded over ``dp`` batch if present).

    The reference has no long-context path to mirror (its TRT engines fix
    max_input_len at build time, conversion_scripts/llama/build.py:96-105);
    this is TPU-native surface. No KV cache is produced — the intended use
    is long-document scoring/training and as the prefill leg of
    long-context serving. tp/ep/pp must be 1 on this mesh (a dp×sp mesh);
    composing sp with in-layer tp is future work and rejected loudly.

    tokens/positions: (B, S) with S divisible by sp. Returns logits
    (B, S, V) float32, sharded (dp, sp) like the inputs.
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.ring_attention import ring_gqa_attention

    n_sp = validate_sp_mesh(mesh, tokens.shape[1], "apply_sp")
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta,
                                cfg.rope_scaling_factor)
    dp = "dp" if int(mesh.shape.get("dp", 1)) > 1 else None

    def fwd(tokens_l, positions_l, params_l):
        h = jnp.take(params_l["embed"], tokens_l, axis=0)

        def attend(q, k, v):
            return ring_gqa_attention(q, k, v, positions_l,
                                      axis_name="sp", axis_size=n_sp), None

        def body(h, lp):
            h, _ = decoder_layer(h, lp, cfg, positions_l, inv_freq,
                                 None, attend=attend)
            return h, None

        h, _ = jax.lax.scan(body, h, params_l["layers"])
        return unembed(params_l, cfg, h)

    seq_spec = P(dp, "sp")
    return shard_map(fwd, mesh=mesh,
                     in_specs=(seq_spec, seq_spec, P()),
                     out_specs=P(dp, "sp", None),
                     check_rep=False)(tokens, positions, params)


def validate_sp_mesh(mesh, S: int, fn_name: str = "sp") -> int:
    """Shared sp-mesh geometry checks (apply_sp / apply_prefill_sp / the
    engine's construction-time validation): sp > 1, no composed
    tp/ep/pp, sequence divisible by sp. Returns the sp size."""
    n_sp = int(mesh.shape.get("sp", 1))
    if n_sp <= 1:
        raise ValueError(f"{fn_name} needs a mesh with sp > 1")
    for ax in ("tp", "ep", "pp"):
        if int(mesh.shape.get(ax, 1)) != 1:
            raise ValueError(
                f"{fn_name} shards only dp×sp; mesh has {ax}="
                f"{mesh.shape[ax]} (composing sp with {ax} is not "
                f"supported)")
    if S % n_sp:
        raise ValueError(
            f"{fn_name}: sequence length {S} not divisible by sp={n_sp}")
    return n_sp


def apply_prefill_sp(params: Params, cfg: LlamaConfig, tokens: jax.Array,
                     positions: jax.Array, mesh, length: jax.Array,
                     ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Sequence-parallel SERVING prefill: ring attention + KV out.

    The sp leg of long-context serving (VERDICT r4 weak #9: ring
    attention drove only score/training): the bucket's activations are
    sharded along the sequence over the mesh's ``sp`` axis — per-device
    prefill activation memory shrinks by ``sp`` — while attention stays
    exact via the KV ring (parallel/ring_attention.py). Unlike
    ``apply_sp`` this RETURNS the per-layer K/V the engine's insert
    scatters into the paged pool, plus the last valid position's logits
    for first-token sampling — full (B, S, V) logits are never
    materialized (at 32k tokens x 32k vocab that transient alone would
    defeat the sharding).

    tokens/positions: (B, S), S divisible by sp; ``length``: () or (B,)
    int32 count of valid tokens (the sample position is length-1; padded
    tail rows produce K/V that the engine's extent accounting never
    attends). Returns ``(k, v, last_logits)`` with k/v
    (L, B, S, KV, hd) sharded over sp along S — the pool scatter
    consumes them without a host round trip — and last_logits (B, V)
    replicated.
    """
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.ring_attention import ring_gqa_attention

    B, S = tokens.shape
    n_sp = validate_sp_mesh(mesh, S, "apply_prefill_sp")
    inv_freq = rope_frequencies(cfg.head_dim, cfg.rope_theta,
                                cfg.rope_scaling_factor)
    # serving prefill is B=1: batch shards over dp only when divisible,
    # otherwise the dp groups replicate the (identical) work
    n_dp = int(mesh.shape.get("dp", 1))
    dp = "dp" if n_dp > 1 and B % n_dp == 0 else None
    length = jnp.broadcast_to(jnp.asarray(length, jnp.int32), (B,))

    def fwd(tokens_l, positions_l, length_l, params_l):
        h = jnp.take(params_l["embed"], tokens_l, axis=0)

        def attend(q, k, v):
            return ring_gqa_attention(q, k, v, positions_l,
                                      axis_name="sp",
                                      axis_size=n_sp), (k, v)

        def body(h, lp):
            h, kv = decoder_layer(h, lp, cfg, positions_l, inv_freq,
                                  None, attend=attend)
            return h, kv

        h, (ks, vs) = jax.lax.scan(body, h, params_l["layers"])
        # Last valid position's hidden state: the row lives on exactly
        # one sp shard — mask-select locally, then one psum makes it
        # replicated. (B, D) is tiny; the unembed runs on it outside.
        sel = (positions_l == (length_l[:, None] - 1))
        h_last = jax.lax.psum(
            jnp.sum(jnp.where(sel[..., None], h, 0.0), axis=1), "sp")
        return ks, vs, h_last

    seq_spec = P(dp, "sp")
    k, v, h_last = shard_map(
        fwd, mesh=mesh,
        in_specs=(seq_spec, seq_spec, P(dp), P()),
        out_specs=(P(None, dp, "sp", None, None),
                   P(None, dp, "sp", None, None), P(dp, None)),
        check_rep=False)(tokens, positions, length, params)
    logits = unembed(params, cfg, h_last[:, None])[:, 0]   # (B, V)
    return k, v, logits


@functools.lru_cache(maxsize=8)
def _score_chunk_step(cfg: LlamaConfig):
    """Jitted per-chunk forward, cached per config — a fresh jit wrapper
    per score() call would re-trace the whole model every request."""
    @jax.jit
    def step(params, cache, tok_c, pos_c):
        logits, cache = apply(params, cfg, tok_c, pos_c, cache,
                              kv_valid_len=pos_c[:, -1] + 1)
        return cache, logits
    return step


@functools.lru_cache(maxsize=8)
def _score_full_fn(cfg: LlamaConfig):
    @jax.jit
    def full(params, tokens, positions):
        logits, _ = apply(params, cfg, tokens, positions)
        return logits
    return full


@functools.lru_cache(maxsize=8)
def _score_sp_fn(cfg: LlamaConfig, mesh):
    @jax.jit
    def sp(params, tokens, positions):
        return apply_sp(params, cfg, tokens, positions, mesh)
    return sp


def score(params: Params, cfg: LlamaConfig, tokens: jax.Array, *,
          mesh=None, chunk: int = 2048) -> jax.Array:
    """Per-token negative log-likelihood of a (long) sequence.

    The served consumer of the long-context machinery: scoring/perplexity
    of documents far beyond the engine's serving window. Two paths:

    - **sp mesh** (``mesh`` with sp > 1): one ``apply_sp`` pass — ring
      attention, activations sequence-sharded, so per-device memory is
      ``1/sp`` of the unsharded forward. The path for sequences whose
      activations cannot fit one chip.
    - **single device**: chunked cached forward — chunks of ``chunk``
      tokens stream through ``apply`` against a persistent KV cache, so
      peak activation memory is one chunk's, with exact attention over
      the full prefix. (KV for the whole sequence — rounded UP to a
      power-of-two compile bucket, up to 2x the sequence's own bytes —
      must still fit; that is the boundary where the sp path takes
      over.)

    tokens: (B, S) int32, S >= 2 (position 0 has no prediction).
    Returns (B, S-1) float32 NLL of token t+1 given tokens <= t.
    """
    B, S = tokens.shape
    if S < 2:
        raise ValueError("score needs at least 2 tokens")
    if chunk < 16:
        raise ValueError(f"chunk must be >= 16, got {chunk}")

    def nll_from(logits, targets):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(
            logp, targets[..., None].astype(jnp.int32), axis=-1)[..., 0]

    if mesh is not None and int(mesh.shape.get("sp", 1)) > 1:
        # pad to an sp multiple; trailing pad positions are causally
        # invisible to real tokens, and their NLL rows are dropped
        n_sp = int(mesh.shape["sp"])
        S_pad = -(-S // n_sp) * n_sp
        padded = jnp.pad(tokens, ((0, 0), (0, S_pad - S)))
        positions = jnp.broadcast_to(jnp.arange(S_pad, dtype=jnp.int32),
                                     (B, S_pad))
        logits = _score_sp_fn(cfg, mesh)(params, padded, positions)
        return nll_from(logits[:, :S - 1], tokens[:, 1:])

    if S <= chunk:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        logits = _score_full_fn(cfg)(params, tokens, positions)
        return nll_from(logits[:, :-1], tokens[:, 1:])

    # Chunked: pad S up to a chunk multiple so every call shares one
    # compiled shape; the pad region is causally invisible to real tokens
    # (absolute-position cache) and its NLL rows are dropped.
    S_pad = -(-S // chunk) * chunk
    padded = jnp.pad(tokens, ((0, 0), (0, S_pad - S)))
    # The CACHE length is bucketed to powers of two (>= chunk): sizing it
    # to S_pad would give every distinct document length its own
    # compiled per-chunk step — seconds of retrace per length, serial
    # under the server's score gate (r4 advisor finding). Power-of-two
    # buckets bound the compile surface to log2(max_len) shapes per
    # chunk size. The padded cache tail is masked by kv_valid_len
    # (never wrong numerics), but it is NOT free: a document just past a
    # boundary allocates up to 2x its own KV bytes and scans the full
    # bucketed length per chunk — the single-device HBM boundary where
    # the sp path takes over moves correspondingly lower.
    cache_len = chunk
    while cache_len < S_pad:
        cache_len *= 2
    # final_norm is never quantized, so its dtype is the activation dtype
    # (embed may be a QTensor dict on quantized trees)
    cache = init_kv_cache(cfg, B, cache_len, params["final_norm"].dtype)
    step = _score_chunk_step(cfg)

    nll_parts = []
    prev_last = None
    for c0 in range(0, S_pad, chunk):
        tok_c = jax.lax.dynamic_slice_in_dim(padded, c0, chunk, axis=1)
        pos_c = jnp.broadcast_to(
            jnp.arange(c0, c0 + chunk, dtype=jnp.int32), (B, chunk))
        cache, logits = step(params, cache, tok_c, pos_c)
        if prev_last is not None:
            # the previous chunk's final position predicts this chunk's
            # first token — stitch across the boundary
            nll_parts.append(nll_from(prev_last, tok_c[:, :1]))
        nll_parts.append(nll_from(logits[:, :-1], tok_c[:, 1:]))
        prev_last = logits[:, -1:]
    return jnp.concatenate(nll_parts, axis=1)[:, :S - 1]
