"""Declarative alerting over the in-process metric history.

An ``AlertRule`` is data, not code: a metric glob, a windowed aggregate
(``last``/``min``/``max``/``avg``/``delta``/``rate``), a comparison, and
a ``for_s`` debounce — evaluated by the ``AlertEngine`` against
``MetricHistory`` (obs/history.py) every sample tick. Each rule runs a
pending→firing→resolved state machine:

- ``ok``: the expression holds for no matching series;
- ``pending``: breached, but not yet continuously for ``for_s``;
- ``firing``: breached for at least ``for_s`` — the transition that
  publishes ``alerts_firing{rule=}`` = 1, logs a structured
  ``alert_firing`` event, and triggers incident capture
  (obs/incidents.py) exactly once per firing episode;
- ``resolved``: the breach cleared while firing — gauge drops to 0,
  ``alert_resolved`` is logged, and the state returns to ``ok`` (a
  later breach starts a NEW episode and may capture again).

Default rules cover the signals the docs already call alert-worthy:
SLO burn rate (``router_slo_attainment``), scheduler cost-model drift
(``sched_cost_drift_ratio``), engine watchdog stalls, breaker flapping
(``breaker_trips_total`` rate), KV restore corruption, heartbeat
staleness, and shed rate. Thresholds/windows are env-tunable
(``ALERT_<RULE>_*`` knobs, docs/configuration.md); rule sets are scoped
per server tier so a router never evaluates engine-local rules and vice
versa.

Every transition increments ``alerts_total{rule=,state=}``; the live
per-rule state is ``alerts_firing{rule=}`` (1 only while firing). Both
are registry-level metrics (like ``shed_total``), documented in
docs/observability.md outside the doc-fenced tables.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Optional

from ..utils.logging import get_logger, log_event
from . import metrics as obs_metrics
from .history import MetricHistory

logger = get_logger(__name__)

_OPS: dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}

#: aggregate name -> key into a history series entry. ``delta``/``rate``
#: are only published for counter-kind series by history.query; for
#: gauges that mirror cumulative engine counters (the engine-stats
#: mirror) the engine computes them from the raw points instead.
_AGGS = ("last", "min", "max", "avg", "delta", "rate")


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative rule: ``agg(metric glob over window_s) op
    threshold``, debounced by ``for_s``."""

    name: str
    metric: str                 # snapshot-key glob (labels included)
    agg: str                    # one of _AGGS
    op: str                     # one of _OPS
    threshold: float
    window_s: float = 120.0     # aggregation window within the history
    for_s: float = 0.0          # continuous-breach debounce
    severity: str = "warning"   # "warning" | "critical"
    summary: str = ""           # one-line operator description

    def __post_init__(self) -> None:
        if self.agg not in _AGGS:
            raise ValueError(f"rule {self.name}: unknown agg {self.agg!r}")
        if self.op not in _OPS:
            raise ValueError(f"rule {self.name}: unknown op {self.op!r}")


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def default_rules(server: str = "chain") -> tuple[AlertRule, ...]:
    """The shipped rule set for one server tier. Read from env on every
    call so deployments (and tests) tune thresholds without code.

    ``server``: "chain" / "model" get the engine-local rules; "router"
    gets the fleet rules. Shed rate is meaningful on every tier.
    """
    engine_rules = (
        AlertRule(
            "engine_watchdog_stall", "engine_watchdog_stalls", "delta",
            ">", _env_f("ALERT_WATCHDOG_THRESHOLD", 0.0),
            window_s=_env_f("ALERT_WATCHDOG_WINDOW_S", 120.0),
            for_s=_env_f("ALERT_WATCHDOG_FOR_S", 0.0),
            severity="critical",
            summary="engine serve loop stalled (watchdog fired) within "
                    "the window"),
        AlertRule(
            "kv_restore_corrupt", "engine_kv_restore_corrupt", "delta",
            ">", 0.0,
            window_s=_env_f("ALERT_KV_CORRUPT_WINDOW_S", 300.0),
            severity="critical",
            summary="KV-tier restore rejected corrupt page payload(s) — "
                    "data-integrity signal, never expected in steady "
                    "state"),
        AlertRule(
            "sched_cost_drift", "engine_sched_cost_drift_ratio", "avg",
            ">", _env_f("ALERT_DRIFT_RATIO_MAX", 1.5),
            window_s=_env_f("ALERT_DRIFT_WINDOW_S", 300.0),
            for_s=_env_f("ALERT_DRIFT_FOR_S", 30.0),
            summary="rounds run slower than the scheduler's cost model "
                    "predicts (drift ratio high) — stale prior or "
                    "device regression"),
    )
    fleet_rules = (
        AlertRule(
            "slo_burn_rate", "router_slo_attainment*", "avg",
            "<", _env_f("ALERT_SLO_ATTAINMENT_MIN", 0.9),
            window_s=_env_f("ALERT_SLO_WINDOW_S", 300.0),
            for_s=_env_f("ALERT_SLO_FOR_S", 10.0),
            severity="critical",
            summary="a replica's rolling SLO attainment burned below "
                    "target over the window"),
        AlertRule(
            "heartbeat_stale", "router_heartbeat_age_seconds*", "last",
            ">", _env_f("ALERT_HEARTBEAT_MAX_AGE_S", 30.0),
            window_s=_env_f("ALERT_HEARTBEAT_WINDOW_S", 60.0),
            severity="critical",
            summary="a replica's last successful heartbeat is older "
                    "than the staleness budget"),
    )
    shared_rules = (
        AlertRule(
            "breaker_flap", "breaker_trips_total*", "rate",
            ">", _env_f("ALERT_BREAKER_FLAP_RATE", 0.1),
            window_s=_env_f("ALERT_BREAKER_WINDOW_S", 300.0),
            summary="a circuit breaker is flapping (trips/s over the "
                    "window above budget)"),
        AlertRule(
            "shed_rate", "shed_total*", "rate",
            ">", _env_f("ALERT_SHED_RATE", 1.0),
            window_s=_env_f("ALERT_SHED_WINDOW_S", 120.0),
            for_s=_env_f("ALERT_SHED_FOR_S", 10.0),
            summary="sustained load shedding (sheds/s over the window "
                    "above budget)"),
    )
    if server == "router":
        return fleet_rules + shared_rules
    return engine_rules + shared_rules


class _RuleState:
    __slots__ = ("state", "since", "breach_since", "fired_at",
                 "resolved_at", "evidence", "episodes")

    def __init__(self) -> None:
        self.state = "ok"
        self.since = time.time()
        self.breach_since: Optional[float] = None
        self.fired_at: Optional[float] = None
        self.resolved_at: Optional[float] = None
        self.evidence: dict = {}
        self.episodes = 0


class AlertEngine:
    """Evaluates rules against a MetricHistory on every tick.

    ``on_fire(rule, record)`` is called exactly once per firing episode
    (on the transition INTO firing, never while it stays firing) — the
    incident black-box's trigger.
    """

    def __init__(self, history: MetricHistory,
                 rules: Optional[tuple[AlertRule, ...]] = None,
                 registry: obs_metrics.Registry = obs_metrics.REGISTRY,
                 on_fire: Optional[Callable[[AlertRule, dict], None]] = None,
                 server: str = "chain"):
        self.history = history
        self.rules = tuple(rules if rules is not None
                           else default_rules(server))
        self.registry = registry
        self.on_fire = on_fire
        self.server = server
        self._states = {r.name: _RuleState() for r in self.rules}
        self._firing_gauge = registry.gauge(
            "alerts_firing",
            "1 while the named alert rule is firing, else 0",
            labelnames=("rule",))
        self._total = registry.counter(
            "alerts_total",
            "alert rule state transitions, by rule and entered state",
            labelnames=("rule", "state"))
        self.ticks = 0

    # ------------------------------------------------------------ evaluate

    def _evaluate(self, rule: AlertRule) -> Optional[dict]:
        """Evidence dict when the rule's expression is breached by any
        matching series, else None."""
        q = self.history.query(metrics=rule.metric, window_s=rule.window_s)
        if not q.get("series"):
            return None
        op = _OPS[rule.op]
        breached = {}
        for key, entry in q["series"].items():
            value = self._agg_value(rule, entry)
            if value is None:
                continue
            if op(float(value), rule.threshold):
                breached[key] = {"value": value, "aggregates": entry}
        if not breached:
            return None
        return {"metric": rule.metric, "agg": rule.agg, "op": rule.op,
                "threshold": rule.threshold, "window_s": rule.window_s,
                "samples": q["samples"], "span_s": q["span_s"],
                "series": breached}

    def _agg_value(self, rule: AlertRule, entry: dict) -> Optional[float]:
        if rule.agg in ("last", "min", "max", "avg"):
            return entry.get(rule.agg)
        # delta/rate: history publishes them for counter-kind series;
        # for gauges mirroring cumulative engine counters (the
        # engine-stats mirror) derive the same reset-aware numbers here.
        if rule.agg == "delta":
            return entry.get("delta", max(0.0, entry["last"] - entry["min"])
                             if entry.get("points", 0) >= 2 else None)
        if rule.agg == "rate":
            if "rate_per_s" in entry:
                return entry["rate_per_s"]
            if entry.get("points", 0) >= 2:
                span = self.history.query(
                    metrics=rule.metric,
                    window_s=rule.window_s).get("span_s") or 0.0
                delta = max(0.0, entry["last"] - entry["min"])
                return delta / span if span > 0 else None
        return None

    # ---------------------------------------------------------------- tick

    def tick(self, now: Optional[float] = None) -> list[dict]:
        """Evaluate every rule once; returns the transition records
        emitted this tick. Called from the history sampler thread (one
        subscriber via ``attach``) or directly by tests/preflight."""
        now = time.time() if now is None else now
        self.ticks += 1
        transitions: list[dict] = []
        for rule in self.rules:
            st = self._states[rule.name]
            evidence = self._evaluate(rule)
            if evidence is not None:
                st.evidence = evidence
                if st.state == "ok":
                    st.breach_since = now
                    if now - st.breach_since >= rule.for_s:
                        transitions.append(self._transition(
                            rule, st, "firing", now))
                    else:
                        transitions.append(self._transition(
                            rule, st, "pending", now))
                elif st.state == "pending":
                    if now - (st.breach_since or now) >= rule.for_s:
                        transitions.append(self._transition(
                            rule, st, "firing", now))
            else:
                if st.state == "firing":
                    transitions.append(self._transition(
                        rule, st, "resolved", now))
                elif st.state == "pending":
                    st.state = "ok"
                    st.since = now
                    st.breach_since = None
        return transitions

    def _transition(self, rule: AlertRule, st: _RuleState,
                    state: str, now: float) -> dict:
        prev = st.state
        st.state = "ok" if state == "resolved" else state
        st.since = now
        if state == "firing":
            st.fired_at = now
            st.episodes += 1
            self._firing_gauge.labels(rule.name).set(1.0)
        elif state == "resolved":
            st.resolved_at = now
            st.breach_since = None
            self._firing_gauge.labels(rule.name).set(0.0)
        self._total.labels(rule.name, state).inc()
        record = {"rule": rule.name, "state": state, "prev": prev,
                  "t": now, "severity": rule.severity,
                  "summary": rule.summary,
                  "for_s": rule.for_s,
                  "evidence": st.evidence if state != "resolved" else {}}
        log_event(logger, f"alert_{state}", rule=rule.name, prev=prev,
                  severity=rule.severity, summary=rule.summary,
                  evidence=record["evidence"])
        if state == "firing" and self.on_fire is not None:
            try:
                self.on_fire(rule, record)
            except Exception:  # noqa: BLE001 — capture must not kill ticks
                logger.warning("alert on_fire handler failed",
                               exc_info=True)
        return record

    # ------------------------------------------------------------ plumbing

    def attach(self) -> "AlertEngine":
        """Subscribe to the history sampler: one tick per sample. The
        inert pin holds transitively — a disabled history never
        samples, so an attached engine never ticks."""
        self.history.on_sample.append(lambda _h: self.tick())
        return self

    def snapshot(self) -> dict:
        """The /debug/alerts body: per-rule spec + live state, firing
        list first-class for dashboards."""
        rules = []
        firing = []
        for rule in self.rules:
            st = self._states[rule.name]
            row = {"rule": rule.name, "state": st.state,
                   "severity": rule.severity, "summary": rule.summary,
                   "metric": rule.metric, "agg": rule.agg, "op": rule.op,
                   "threshold": rule.threshold,
                   "window_s": rule.window_s, "for_s": rule.for_s,
                   "since": round(st.since, 3),
                   "episodes": st.episodes,
                   "evidence": st.evidence if st.state in
                   ("pending", "firing") else {}}
            rules.append(row)
            if st.state == "firing":
                firing.append(rule.name)
        return {"enabled": self.history.enabled, "server": self.server,
                "ticks": self.ticks, "rules": rules, "firing": firing}

    def firing(self) -> list[str]:
        return [name for name, st in self._states.items()
                if st.state == "firing"]


def debug_alerts_response(request, engine: Optional[AlertEngine]):
    """Shared ``GET /debug/alerts`` body for all three servers."""
    from aiohttp import web

    if engine is None:
        return web.json_response({"enabled": False, "rules": [],
                                  "firing": []})
    return web.json_response(engine.snapshot())
