"""NeMo ``.nemo`` checkpoint importer.

The reference converts .nemo tarballs by delegating to NeMo's own TRT
exporter after a config sanity-read (reference: model_server/conversion/
nemo.py:35-65 — TarFile open, model_config.yaml check, nemo.export).
Here the tarball is read directly: ``model_config.yaml`` for shape
validation plus ``model_weights.ckpt`` (a torch state dict in megatron
naming) mapped onto the stacked param tree. Handles the two megatron
fusions:

- ``self_attention.query_key_value.weight``: per-head-group interleaved
  [q..q k v] rows, de-interleaved into wq/wk/wv (GQA-aware);
- ``mlp.dense_h_to_4h.weight``: swiglu-fused [gate; up] rows, split.

NeMo's rotary embedding uses the same half-split (rotate-half) layout as
HF, so no RoPE permutation applies (unlike Meta .pth imports).
"""

from __future__ import annotations

import os
import tarfile
import tempfile
from typing import Any

import jax.numpy as jnp
import numpy as np

from ..utils.errors import ModelLoadError
from .configs import LlamaConfig

Params = dict[str, Any]

_PREFIX = "model.language_model."


def _find_nemo(path: str) -> str:
    if os.path.isfile(path) and path.endswith(".nemo"):
        return path
    if os.path.isdir(path):
        for n in sorted(os.listdir(path)):
            if n.endswith(".nemo"):
                return os.path.join(path, n)
    raise ModelLoadError(f"no .nemo archive at {path}")


def _read_archive(nemo_path: str) -> tuple[dict, dict[str, np.ndarray]]:
    import torch
    import yaml
    with tarfile.open(nemo_path) as tar, \
            tempfile.TemporaryDirectory() as td:
        names = tar.getnames()
        cfg_name = next((n for n in names
                         if n.endswith("model_config.yaml")), None)
        ckpt_name = next((n for n in names
                          if n.endswith(("model_weights.ckpt",
                                         "model_weights.pt"))), None)
        if cfg_name is None or ckpt_name is None:
            raise ModelLoadError(
                f"{nemo_path}: expected model_config.yaml + "
                f"model_weights.ckpt in archive (found {names[:8]})")
        with tar.extractfile(cfg_name) as f:  # type: ignore[union-attr]
            config = yaml.safe_load(f.read()) or {}
        tar.extract(ckpt_name, td, filter="data")
        state = torch.load(os.path.join(td, ckpt_name),
                           map_location="cpu", weights_only=True)
    tensors = {}
    for key, t in state.items():
        tensors[key] = t.to(torch.float32).numpy() \
            if t.dtype in (torch.float16, torch.bfloat16) else t.numpy()
    return config, tensors


def _split_qkv(fused: np.ndarray, cfg: LlamaConfig
               ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Megatron fused QKV (rows [q*g k v] per KV group) -> q, k, v with
    our (in, out) orientation."""
    D = fused.shape[1]
    hd, KV = cfg.head_dim, cfg.num_kv_heads
    g = cfg.num_heads // KV
    grouped = fused.reshape(KV, (g + 2) * hd, D)
    q = grouped[:, :g * hd, :].reshape(KV * g * hd, D)
    k = grouped[:, g * hd:(g + 1) * hd, :].reshape(KV * hd, D)
    v = grouped[:, (g + 1) * hd:, :].reshape(KV * hd, D)
    return q.T, k.T, v.T


def load_nemo_checkpoint(path: str, cfg: LlamaConfig,
                         dtype: jnp.dtype = jnp.bfloat16) -> Params:
    nemo_path = _find_nemo(path)
    config, tensors = _read_archive(nemo_path)

    # config sanity-read (reference: conversion/nemo.py:46-52)
    declared = config.get("num_layers")
    if declared is not None and int(declared) != cfg.num_layers:
        raise ModelLoadError(
            f"{nemo_path}: model_config.yaml num_layers={declared} but "
            f"target config has {cfg.num_layers}")

    def get(name: str) -> np.ndarray:
        for key in (_PREFIX + name, "model." + name, name):
            if key in tensors:
                return tensors[key]
        raise ModelLoadError(f"{nemo_path}: missing tensor {name!r}")

    L, F = cfg.num_layers, cfg.intermediate_size
    gptnext = cfg.mlp == "squared_relu"
    ln1p = cfg.norm == "layernorm1p"
    keys = ["attn_norm", "mlp_norm", "wq", "wk", "wv", "wo",
            "w_up", "w_down"]
    if not gptnext:
        keys.append("w_gate")
    if ln1p:
        keys += ["attn_norm_b", "mlp_norm_b"]
    acc: dict[str, list] = {k: [None] * L for k in keys}
    for i in range(L):
        base = f"encoder.layers.{i}."
        acc["attn_norm"][i] = get(base + "input_layernorm.weight")
        acc["mlp_norm"][i] = get(base + "post_attention_layernorm.weight")
        if ln1p:
            acc["attn_norm_b"][i] = get(base + "input_layernorm.bias")
            acc["mlp_norm_b"][i] = get(
                base + "post_attention_layernorm.bias")
        q, k, v = _split_qkv(
            get(base + "self_attention.query_key_value.weight"), cfg)
        acc["wq"][i], acc["wk"][i], acc["wv"][i] = q, k, v
        acc["wo"][i] = get(base + "self_attention.dense.weight").T
        fused_mlp = get(base + "mlp.dense_h_to_4h.weight")
        if gptnext:
            # GPT-Next MLP is non-gated: h_to_4h has exactly F rows
            if fused_mlp.shape[0] != F:
                raise ModelLoadError(
                    f"{nemo_path}: expected squared-relu dense_h_to_4h "
                    f"with {F} rows, got {fused_mlp.shape[0]}")
            acc["w_up"][i] = fused_mlp.T
        else:
            if fused_mlp.shape[0] != 2 * F:
                raise ModelLoadError(
                    f"{nemo_path}: expected swiglu-fused dense_h_to_4h "
                    f"with {2 * F} rows, got {fused_mlp.shape[0]}")
            acc["w_gate"][i] = fused_mlp[:F].T
            acc["w_up"][i] = fused_mlp[F:].T
        acc["w_down"][i] = get(base + "mlp.dense_4h_to_h.weight").T

    layers = {k: jnp.asarray(np.stack(v), dtype) for k, v in acc.items()}
    params: Params = {
        "embed": jnp.asarray(get("embedding.word_embeddings.weight"),
                             dtype),
        "layers": layers,
        "final_norm": jnp.asarray(
            get("encoder.final_layernorm.weight"), dtype),
    }
    if ln1p:
        params["final_norm_b"] = jnp.asarray(
            get("encoder.final_layernorm.bias"), dtype)
    try:
        params["lm_head"] = jnp.asarray(get("output_layer.weight").T, dtype)
    except ModelLoadError:
        if not cfg.tie_word_embeddings:
            raise
    return params
