"""Hierarchical chunking + auto-merging retrieval tests
(the first-party analogue of the reference's
notebooks/04_llamaindex_hier_node_parser.ipynb pipeline)."""

import pytest

from generativeaiexamples_tpu.chains.hier_splitter import (
    AutoMergingIndex, HierarchicalSplitter)
from generativeaiexamples_tpu.embed.encoder import HashEmbedder
from generativeaiexamples_tpu.retrieval.docstore import DocumentIndex


def _text(n_sentences=120):
    return " ".join(
        f"Sentence {i} about topic {'alpha' if i < 60 else 'beta'}."
        for i in range(n_sentences))


def test_split_builds_strict_tree():
    sp = HierarchicalSplitter(chunk_sizes=(256, 64, 16))
    nodes = sp.split(_text())
    by_id = {n.id: n for n in nodes}
    roots = [n for n in nodes if n.parent is None]
    leaves = sp.leaves(nodes)
    assert roots and leaves
    assert all(n.level == 0 for n in roots)
    for n in nodes:
        for c in n.children:
            assert by_id[c].parent == n.id
            assert by_id[c].level == n.level + 1
            # child text is contained in the parent window
            assert by_id[c].text in n.text or by_id[c].text.strip() in n.text
    # leaves are exactly the deepest level
    assert {n.level for n in leaves} == {2}


def test_chunk_sizes_must_decrease():
    with pytest.raises(ValueError, match="strictly decrease"):
        HierarchicalSplitter(chunk_sizes=(128, 128))
    with pytest.raises(ValueError, match="strictly decrease"):
        HierarchicalSplitter(chunk_sizes=(64, 256))


def test_automerge_replaces_children_with_parent():
    emb = HashEmbedder(dim=64)
    ami = AutoMergingIndex(DocumentIndex(emb),
                           HierarchicalSplitter(chunk_sizes=(256, 64, 16)),
                           merge_ratio=0.5)
    n_leaves = ami.add_document(_text(), source="doc")
    assert n_leaves >= 8
    # retrieve with k large enough that many sibling leaves hit: they
    # must merge upward into larger windows
    docs = ami.retrieve("topic alpha", k=min(n_leaves, 12))
    assert docs
    assert any(d.metadata.get("merged_depth", 0) >= 1 for d in docs), \
        [d.metadata for d in docs]
    merged = next(d for d in docs
                  if d.metadata.get("merged_depth", 0) >= 1)
    assert merged.metadata["level"] < 2          # coarser than a leaf
    assert merged.metadata["merged_children"] > 1
    # no duplicate nodes, scores ordered
    keys = [(d.metadata["tree"], d.metadata["node_id"]) for d in docs]
    assert len(keys) == len(set(keys))
    scores = [d.score for d in docs]
    assert scores == sorted(scores, reverse=True)


def test_two_documents_with_same_source_keep_separate_trees():
    """Node ids restart per document; two docs sharing a source string
    must not cross-merge (regression: source-keyed tree map)."""
    emb = HashEmbedder(dim=64)
    ami = AutoMergingIndex(DocumentIndex(emb),
                           HierarchicalSplitter(chunk_sizes=(256, 64, 16)))
    ami.add_document(_text(), source="same.txt")
    ami.add_document("Entirely different subject: cooking pasta. " * 30,
                     source="same.txt")
    docs = ami.retrieve("topic alpha", k=12)
    assert docs
    # every returned window's text must come from the tree it claims
    for d in docs:
        node = ami._trees[d.metadata["tree"]][d.metadata["node_id"]]
        assert d.text == node.text


def test_single_hit_is_not_merged():
    emb = HashEmbedder(dim=64)
    ami = AutoMergingIndex(DocumentIndex(emb),
                           HierarchicalSplitter(chunk_sizes=(256, 64, 16)))
    ami.add_document(_text(), source="doc")
    docs = ami.retrieve("topic alpha", k=1)
    assert len(docs) == 1
    assert docs[0].metadata.get("merged_depth", 0) == 0
    assert docs[0].metadata["level"] == 2
