"""Fused on-device RAG admission: the whole retrieve->prompt->prefill hot
path as ONE XLA dispatch.

The reference's QA chatbot crosses three process boundaries on its hot
path — embed (GPU), Milvus search (gRPC), Triton prefill (gRPC)
(reference: RetrievalAugmentedGeneration/common/server.py:121-142 and
examples/developer_rag/chains.py:101-127). The host round trips between
them are pure latency; on a remote-attached TPU each blocking
device<->host sync costs tens of milliseconds, so a chatbot TTFT pays
them twice (embedding readback, then first-token readback).

TPU-native answer: keep the corpus ON the device and compile the chain
itself into the admission program —

  query tokens ──► e5 encoder ──► dot-product top-k over the corpus
      ──► token-space prompt assembly (template + retrieved chunks)
      ──► prefill + sample + KV-insert (the engine's fused admission)

One host->device transfer in (the query's tokens, both vocabularies),
one device->host readback out (first token + assembled length + doc
ids). Retrieval context never touches the host.

Token-space assembly note: chunk token ids are concatenated at chunk
boundaries instead of re-tokenizing the joined string, so a BPE merge
that would span a boundary ("...end" + "\\n\\nThe...") stays split. The
token sequences differ from the host path only at those joins — the
rendered text is identical.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np


@dataclass(frozen=True)
class FusedRagSpec:
    """Static geometry + template tokens for the fused program.

    Prompt layout: ``prefix ⧺ [sep? doc_i]* ⧺ mid ⧺ question ⧺ suffix``
    (sep before every doc but the first — the token-space analogue of
    "\\n\\n".join). All lengths are compile-time constants.
    """
    prefix_ids: tuple          # template up to {context_str} (incl. BOS)
    sep_ids: tuple             # joiner between retrieved chunks
    mid_ids: tuple             # between {context_str} and {query_str}
    suffix_ids: tuple          # template tail after {query_str}
    top_k: int = 4             # reference: chains.py:117 top-4
    ctx_budget: int = 1500     # reference: common/utils.py:91 token cap
    bucket: int = 1024         # assembled-prompt static length
    chunk_tokens: int = 256    # per-chunk token capacity (C)
    q_bucket: int = 64         # question token capacity (LLM vocab)
    enc_bucket: int = 128      # question token capacity (encoder vocab)


def build_prompt_parts(rag_template: str, tokenizer) -> dict:
    """Split the RAG template at its placeholders and tokenize each part
    (prefix gets the BOS). Sentinel-based so any template text works."""
    probe = rag_template.format(context_str="\x00", query_str="\x01")
    prefix, rest = probe.split("\x00", 1)
    mid, suffix = rest.split("\x01", 1)
    return {
        "prefix_ids": tuple(tokenizer.encode(prefix, add_bos=True)),
        "sep_ids": tuple(tokenizer.encode("\n\n", add_bos=False)),
        "mid_ids": tuple(tokenizer.encode(mid, add_bos=False)),
        "suffix_ids": tuple(tokenizer.encode(suffix, add_bos=False)),
    }


class FusedRag:
    """Holds the encoder params, the device-resident corpus, and the
    assembly function; the engine jits it fused with its admission."""

    def __init__(self, enc_params, enc_cfg, spec: FusedRagSpec):
        import jax.numpy as jnp
        self.enc_params = enc_params
        self.enc_cfg = enc_cfg
        self.spec = spec
        self.corpus = {
            "emb": jnp.zeros((8, enc_cfg.hidden_size), jnp.float32),
            "toks": jnp.zeros((8, spec.chunk_tokens), jnp.int32),
            "lens": jnp.zeros((8,), jnp.int32),
            "n": jnp.int32(0),
        }

    # --------------------------------------------------------- corpus

    def set_corpus(self, emb: np.ndarray, toks: np.ndarray,
                   lens: np.ndarray) -> None:
        """Upload the retrieval corpus. Capacity pads to the next power
        of two so incremental ingest reuses compiled programs."""
        import jax
        import jax.numpy as jnp
        n, d = emb.shape
        cap = 8
        while cap < n:
            cap *= 2
        C = self.spec.chunk_tokens
        emb_p = np.zeros((cap, d), np.float32)
        emb_p[:n] = emb
        toks_p = np.zeros((cap, C), np.int32)
        toks_p[:n] = toks[:, :C]
        lens_p = np.zeros((cap,), np.int32)
        lens_p[:n] = np.minimum(lens, C)
        self.corpus = {
            "emb": jax.device_put(jnp.asarray(emb_p)),
            "toks": jax.device_put(jnp.asarray(toks_p)),
            "lens": jax.device_put(jnp.asarray(lens_p)),
            "n": jnp.int32(n),
        }

    # ------------------------------------------------------- assembly

    def assemble(self, enc_params, corpus, q_enc, q_llm, q_llm_len):
        """Device-side: embed the query, pick top-k chunks under the
        token budget, scatter template + chunks + question into one
        (bucket,) token row. Returns (tokens, length, top_ids).

        ``enc_params`` is an explicit argument (not read from self): the
        engine jits this composed with its admission program, and state
        read through ``self`` would leak tracers across traces."""
        import jax
        import jax.numpy as jnp

        from ..models import encoder as enc

        spec = self.spec
        S = spec.bucket
        K = spec.top_k
        C = spec.chunk_tokens

        hidden = enc.apply(enc_params, self.enc_cfg,
                           q_enc[0][None], q_enc[1][None])
        qvec = enc.mean_pool(hidden, q_enc[1][None], normalize=True)[0]

        emb = corpus["emb"]
        scores = emb @ qvec.astype(emb.dtype)                   # (Ncap,)
        live = jnp.arange(emb.shape[0]) < corpus["n"]
        scores = jnp.where(live, scores, -jnp.inf)
        _, top_ids = jax.lax.top_k(scores, K)
        picked = jnp.arange(K) < jnp.minimum(K, corpus["n"])
        dlens = jnp.where(picked, corpus["lens"][top_ids], 0)   # (K,)
        dtoks = corpus["toks"][top_ids]                         # (K, C)

        sep_len = len(spec.sep_ids)
        pre_len = len(spec.prefix_ids)
        # context budget: keep the leading run of docs that fits
        # (reference: LimitRetrievedNodesLength, common/utils.py:96-118)
        costs = jnp.where(dlens > 0,
                          dlens + jnp.where(jnp.arange(K) > 0, sep_len, 0),
                          0)
        keep = (jnp.cumsum(costs) <= spec.ctx_budget) & (dlens > 0)
        costs = jnp.where(keep, costs, 0)
        doc_off = pre_len + jnp.concatenate(
            [jnp.zeros((1,), jnp.int32),
             jnp.cumsum(costs)[:-1].astype(jnp.int32)])
        total_ctx = jnp.sum(costs)

        out = jnp.zeros((S,), jnp.int32)
        DROP = S  # out-of-range index -> scatter mode="drop"

        def place(out, ids, offset, valid_len, on):
            """Scatter a static token tuple / padded row at a dynamic
            offset; positions beyond valid_len (or when not on) drop."""
            ids = jnp.asarray(ids, jnp.int32)
            pos = jnp.arange(ids.shape[0], dtype=jnp.int32)
            idx = jnp.where(on & (pos < valid_len), offset + pos, DROP)
            return out.at[idx].set(ids, mode="drop")

        out = place(out, spec.prefix_ids, jnp.int32(0),
                    jnp.int32(pre_len), jnp.bool_(True))
        for i in range(K):
            if i > 0 and sep_len:
                out = place(out, spec.sep_ids, doc_off[i],
                            jnp.int32(sep_len), keep[i])
            tok_off = doc_off[i] + (sep_len if i > 0 else 0)
            out = place(out, dtoks[i], tok_off, dlens[i], keep[i])

        mid_off = pre_len + total_ctx
        out = place(out, spec.mid_ids, mid_off, jnp.int32(len(spec.mid_ids)),
                    jnp.bool_(True))
        q_off = mid_off + len(spec.mid_ids)
        out = place(out, q_llm, q_off, q_llm_len, jnp.bool_(True))
        suf_off = q_off + q_llm_len
        out = place(out, spec.suffix_ids, suf_off,
                    jnp.int32(len(spec.suffix_ids)), jnp.bool_(True))
        length = jnp.minimum(suf_off + len(spec.suffix_ids), S)
        return out, length.astype(jnp.int32), top_ids.astype(jnp.int32)


def corpus_rows(texts: Sequence[str], tokenizer, chunk_tokens: int):
    """Host-side: tokenize chunk texts (no BOS) into padded (N, C) rows
    for ``FusedRag.set_corpus``."""
    n = len(texts)
    toks = np.zeros((n, chunk_tokens), np.int32)
    lens = np.zeros((n,), np.int32)
    for i, t in enumerate(texts):
        ids = tokenizer.encode(t, add_bos=False)[:chunk_tokens]
        toks[i, :len(ids)] = ids
        lens[i] = len(ids)
    return toks, lens
