"""Tier-1 guard: docs/observability.md's engine gauge table stays in
sync with Engine.stats(), and its router metric table with
router.metrics.ROUTER_METRICS (tools/check_metrics_docs.py) — a rename
on either side can't silently orphan the docs, and a new metric can't
ship undocumented."""

import pytest

from tools.check_metrics_docs import (BEGIN, END, ROUNDS_BEGIN, ROUNDS_END,
                                      ROUTER_BEGIN, ROUTER_END, check,
                                      documented_gauges,
                                      documented_round_metrics,
                                      documented_router_metrics)


def test_docs_gauge_table_matches_engine_stats():
    assert check() == []


def test_checker_flags_ghost_and_missing_gauges():
    """Sanity of the checker itself: a documented gauge with no stats key
    is a ghost; dropping a documented row leaves a stats key missing."""
    ghost = (f"{BEGIN}\n| `engine_requests` | x |\n"
             f"| `engine_not_a_real_stat` | x |\n{END}\n"
             f"{ROUTER_BEGIN}{ROUTER_END}"   # other fences: own tests
             f"{ROUNDS_BEGIN}{ROUNDS_END}")
    errors = check(ghost)
    assert any("engine_not_a_real_stat" in e for e in errors)
    assert any("engine_tokens_generated" in e for e in errors)  # missing


def test_checker_requires_markers():
    with pytest.raises(SystemExit):
        documented_gauges("no markers here")


def _with_router_fence(rows: str) -> str:
    """A doc body whose ENGINE fence is intact (read from the real doc)
    but whose router fence is replaced by ``rows`` — isolates the router
    direction of the check."""
    import tools.check_metrics_docs as mod
    with open(mod.DOC_PATH) as f:
        text = f.read()
    start = text.index(ROUTER_BEGIN)
    end = text.index(ROUTER_END) + len(ROUTER_END)
    return text[:start] + f"{ROUTER_BEGIN}\n{rows}\n{ROUTER_END}" \
        + text[end:]


def test_checker_flags_ghost_and_missing_router_metrics():
    errors = check(_with_router_fence(
        "| `router_replicas_healthy` | x |\n"
        "| `router_not_a_real_metric` | x |"))
    assert any("router_not_a_real_metric" in e for e in errors)
    assert any("router_placed_total" in e for e in errors)  # missing


def test_router_docs_names_ignore_label_suffixes():
    """`router_placed_total{replica=}` documents router_placed_total —
    the label hint in the docs is prose, not part of the name."""
    docs = documented_router_metrics(
        f"{ROUTER_BEGIN}\n| `router_placed_total{{replica=}}` | x |\n"
        f"{ROUTER_END}")
    assert docs == {"router_placed_total"}


def test_checker_requires_router_markers():
    with pytest.raises(SystemExit):
        documented_router_metrics(f"{BEGIN} {END} no router fence")


def _with_rounds_fence(rows: str) -> str:
    """The real doc with only the ROUND fence replaced — isolates the
    round-telemetry direction of the check."""
    import tools.check_metrics_docs as mod
    with open(mod.DOC_PATH) as f:
        text = f.read()
    start = text.index(ROUNDS_BEGIN)
    end = text.index(ROUNDS_END) + len(ROUNDS_END)
    return text[:start] + f"{ROUNDS_BEGIN}\n{rows}\n{ROUNDS_END}" \
        + text[end:]


def test_checker_flags_ghost_and_missing_round_metrics():
    errors = check(_with_rounds_fence(
        "| `engine_rounds_total` | x |\n"
        "| `engine_round_not_real` | x |"))
    assert any("engine_round_not_real" in e for e in errors)
    assert any("sched_cost_drift_ratio" in e for e in errors)  # missing


def test_checker_requires_round_markers():
    with pytest.raises(SystemExit):
        documented_round_metrics(f"{BEGIN} {END} no round fence")
