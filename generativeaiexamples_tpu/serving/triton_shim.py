"""Triton-compatible HTTP shim: the ensemble tensor API, minus Triton.

The reference's public serving surface is the Triton ensemble tensor API —
``text_input``, ``max_tokens``, ``top_k``, ``top_p``, ``temperature``,
``length_penalty``, ``repetition_penalty``, ``random_seed``, ``beam_width``,
``stream``, ``stop_words``, ``bad_words`` in, ``text_output`` out
(reference: ensemble_models/llama/ensemble/config.pbtxt:27-117; the client
builds exactly this input list, model_server_client/trt_llm.py:344-355).

This shim keeps those names and semantics over Triton's standard HTTP
generate extension (``/v2/models/{model}/generate`` and
``/generate_stream``) plus the health/ready endpoints the reference's
client polls (reference: trt_llm.py:259-271 ``load_model`` waits on model
readiness), so existing Triton-generate clients can point at the TPU stack
unchanged.
"""

from __future__ import annotations

import json
from typing import Any

from aiohttp import web

from ..engine.sampling_params import SamplingParams
from ..obs import metrics as obs_metrics
from ..utils.errors import EngineError
from ..obs.tracing import instrumented
from .streaming import iterate_in_thread


def _first(v: Any) -> Any:
    """Triton clients send scalars as [v] or [[v]]; unwrap."""
    while isinstance(v, (list, tuple)) and v:
        v = v[0]
    return v


def _params_from_triton(body: dict, max_output: int) -> SamplingParams:
    def get(name: str, default, cast):
        v = body.get(name)
        return cast(_first(v)) if v is not None else default

    def words(name: str) -> list[str]:
        v = body.get(name) or []
        if isinstance(v, str):
            v = [v]
        return [str(s) for s in v if s]

    beam = get("beam_width", 1, int)
    if beam != 1:
        raise web.HTTPBadRequest(text="beam_width != 1 is not supported")
    try:
        return SamplingParams(
            max_tokens=min(get("max_tokens", 100, int), max_output),
            temperature=get("temperature", 1.0, float),
            top_k=get("top_k", 1, int),
            top_p=get("top_p", 0.0, float),
            repetition_penalty=get("repetition_penalty", 1.0, float),
            length_penalty=get("length_penalty", 1.0, float),
            random_seed=get("random_seed", 0, int),
            stop_words=words("stop_words"),
            bad_words=words("bad_words"),
        )
    except ValueError as exc:  # e.g. length_penalty without beam search
        raise web.HTTPBadRequest(text=str(exc)) from exc


def add_triton_routes(app: web.Application, engine, model_name: str = "ensemble",
                      max_output: int = 512) -> None:
    known = {model_name, "ensemble"}

    async def server_ready(request: web.Request) -> web.Response:
        return web.json_response({"ready": True})

    async def model_ready(request: web.Request) -> web.Response:
        if request.match_info["model"] not in known:
            raise web.HTTPNotFound(
                text=f"unknown model {request.match_info['model']!r}")
        return web.json_response({"ready": True})

    async def model_index(request: web.Request) -> web.Response:
        # parity: GrpcTritonClient.get_model_list / load_model discovery
        return web.json_response(
            [{"name": n, "state": "READY"} for n in sorted(known)])

    def _check_model(request: web.Request) -> None:
        if request.match_info["model"] not in known:
            raise web.HTTPNotFound(
                text=f"unknown model {request.match_info['model']!r}")

    @instrumented("triton_generate")
    async def generate(request: web.Request) -> web.Response:
        _check_model(request)
        body = await request.json()
        text_input = str(_first(body.get("text_input", "")))
        if not text_input:
            raise web.HTTPBadRequest(text="text_input is required")
        try:
            params = _params_from_triton(body, max_output)
        except (ValueError, TypeError) as exc:
            raise web.HTTPBadRequest(
                text=f"invalid parameters: {exc}") from exc
        timer = obs_metrics.RequestTimer("triton_generate")
        engine.start()
        try:
            stream = engine.stream_text(text_input, params)
        except EngineError as exc:  # invalid request (length, bad_words...)
            raise web.HTTPBadRequest(text=str(exc)) from exc
        chunks = []
        async for chunk in iterate_in_thread(iter(stream)):
            timer.token(1)  # one chunk ≈ one decode step
            chunks.append(chunk)
        timer.finish()
        return web.json_response({"model_name": request.match_info["model"],
                                  "text_output": "".join(chunks)})

    @instrumented("triton_generate_stream")
    async def generate_stream(request: web.Request) -> web.StreamResponse:
        _check_model(request)
        body = await request.json()
        text_input = str(_first(body.get("text_input", "")))
        if not text_input:
            raise web.HTTPBadRequest(text="text_input is required")
        try:
            params = _params_from_triton(body, max_output)
        except (ValueError, TypeError) as exc:
            raise web.HTTPBadRequest(
                text=f"invalid parameters: {exc}") from exc
        timer = obs_metrics.RequestTimer("triton_generate")
        engine.start()
        try:
            stream = engine.stream_text(text_input, params)
        except EngineError as exc:  # invalid request (length, bad_words...)
            raise web.HTTPBadRequest(text=str(exc)) from exc

        resp = web.StreamResponse(
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache"})
        await resp.prepare(request)
        try:
            async for chunk in iterate_in_thread(iter(stream)):
                timer.token(1)  # one chunk ≈ one decode step
                # decoupled-mode delta responses
                # (reference: config.pbtxt.j2 decoupled_mode, client
                # callback trt_llm.py:417-442 checks triton_final_response)
                payload = {"model_name": request.match_info["model"],
                           "text_output": chunk,
                           "triton_final_response": False}
                await resp.write(f"data: {json.dumps(payload)}\n\n".encode())
            final = {"model_name": request.match_info["model"],
                     "text_output": "", "triton_final_response": True,
                     "finish_reason": stream.finish_reason}
            await resp.write(f"data: {json.dumps(final)}\n\n".encode())
        except (ConnectionResetError, ConnectionError):
            pass  # client went away mid-stream
        finally:
            timer.finish()
        await resp.write_eof()
        return resp

    app.router.add_get("/v2/health/ready", server_ready)
    app.router.add_get("/v2/health/live", server_ready)
    app.router.add_post("/v2/repository/index", model_index)
    app.router.add_get("/v2/models/{model}/ready", model_ready)
    app.router.add_post("/v2/models/{model}/generate", generate)
    app.router.add_post("/v2/models/{model}/generate_stream", generate_stream)
