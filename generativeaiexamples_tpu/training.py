"""Training step for fine-tuning workflows.

The reference ships model-customization recipes (LoRA/SFT notebooks for
Gemma via NeMo, reference: models/Gemma/lora.ipynb, sft.ipynb) but no
in-repo training loop. Here fine-tuning is first-class: a jit-compilable
train step over any mesh (dp/tp/pp/ep shardings), used both by the
fine-tuning tools and by the multi-chip dry-run validation.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax

from .models import llama
from .models.configs import LlamaConfig


def cross_entropy_loss(logits: jax.Array, targets: jax.Array,
                       mask: jax.Array) -> jax.Array:
    """Masked mean token cross-entropy. logits (B,S,V), targets/mask (B,S)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    maskf = mask.astype(jnp.float32)
    return jnp.sum(nll * maskf) / jnp.maximum(jnp.sum(maskf), 1.0)


def make_train_step(cfg: LlamaConfig, optimizer: optax.GradientTransformation,
                    mesh=None, n_microbatches: int = 2):
    """Build a (params, opt_state, batch) -> (params, opt_state, loss) step.

    ``batch`` = {"tokens": (B,S), "targets": (B,S), "mask": (B,S)} plus an
    optional ``"length"`` (B,). ``mask`` is the LOSS mask; attention
    validity defaults to ``sum(mask)`` (right-padded plain-LM batches)
    but an SFT batch that masks prompt tokens OUT of the loss must pass
    the true per-row token count as ``length`` — otherwise the masked
    prompt would also vanish from attention.
    jit it with shardings from ``parallel.llama_param_specs`` to train over
    a mesh; XLA inserts the gradient all-reduces over dp and the TP
    collectives over tp. When ``mesh`` has pp > 1 the forward runs the
    GPipe microbatch schedule (parallel/pipeline.py) — layers stream
    stage-to-stage over ``ppermute`` and gradients flow back through the
    schedule.
    """
    if mesh is not None and dict(mesh.shape).get("pp", 1) > 1:
        from .parallel.pipeline import pipeline_loss_fn
        loss_fn = pipeline_loss_fn(mesh, cfg, n_microbatches=n_microbatches)
    else:
        def loss_fn(params: llama.Params,
                    batch: dict[str, jax.Array]) -> jax.Array:
            B, S = batch["tokens"].shape
            positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32),
                                         (B, S))
            length = batch.get("length")
            if length is None:
                length = jnp.sum(batch["mask"], axis=-1)
            logits, _ = llama.apply(
                params, cfg, batch["tokens"], positions,
                kv_valid_len=length)
            return cross_entropy_loss(logits, batch["targets"],
                                      batch["mask"])

    def train_step(params: llama.Params, opt_state: Any,
                   batch: dict[str, jax.Array]):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step
