"""Speech (ASR/TTS) boundary — external Riva services, import-gated.

Parity with the reference's speech layer (reference:
frontend/frontend/asr_utils.py — Riva gRPC streaming speech-to-text into
the message box; tts_utils.py — text-to-speech of responses, with
language/voice discovery from the server config). Riva stays an external
service boundary (SURVEY.md §2 native-component 11: out of scope to
reimplement the speech models); these classes wrap its gRPC API when the
``riva.client`` package is present and degrade to a clear error when not.
"""

from __future__ import annotations

from typing import Iterator, Optional

from ..utils.errors import ConfigError


def _require_riva():
    try:
        import riva.client  # type: ignore
        return riva.client
    except ImportError as exc:
        raise ConfigError(
            "speech features require the 'nvidia-riva-client' package and a "
            "running Riva server (external boundary, like the reference); "
            "install riva-client and set the server URI") from exc


class ASRClient:
    """Streaming speech-to-text (reference: asr_utils.py ``ASRSession``)."""

    def __init__(self, server: str = "localhost:50051",
                 language_code: str = "en-US", sample_rate_hz: int = 16000):
        riva = _require_riva()
        self._auth = riva.Auth(uri=server)
        self._service = riva.ASRService(self._auth)
        self._riva = riva
        self.language_code = language_code
        self.sample_rate_hz = sample_rate_hz

    def transcribe_streaming(self, audio_chunks: Iterator[bytes],
                             ) -> Iterator[str]:
        """Yield partial transcripts for streaming audio
        (reference: asr_utils.py ``transcribe_streaming``)."""
        riva = self._riva
        config = riva.StreamingRecognitionConfig(
            config=riva.RecognitionConfig(
                language_code=self.language_code,
                sample_rate_hertz=self.sample_rate_hz,
                max_alternatives=1, enable_automatic_punctuation=True),
            interim_results=True)
        for response in self._service.streaming_response_generator(
                audio_chunks, config):
            for result in response.results:
                if result.alternatives:
                    yield result.alternatives[0].transcript

    def transcribe(self, audio: bytes) -> str:
        """Offline recognition of a complete recording (the converse
        page's mic posts one 16 kHz LINEAR_PCM WAV): all final segments
        concatenated — a multi-utterance recording keeps every sentence,
        not just the last recognizer yield."""
        riva = self._riva
        config = riva.RecognitionConfig(
            encoding=riva.AudioEncoding.LINEAR_PCM,
            language_code=self.language_code,
            sample_rate_hertz=self.sample_rate_hz,
            max_alternatives=1, enable_automatic_punctuation=True)
        response = self._service.offline_recognize(audio, config)
        return " ".join(
            r.alternatives[0].transcript.strip()
            for r in response.results if r.alternatives).strip()


class TTSClient:
    """Text-to-speech (reference: tts_utils.py ``text_to_speech``)."""

    def __init__(self, server: str = "localhost:50051",
                 language_code: str = "en-US",
                 voice_name: Optional[str] = None,
                 sample_rate_hz: int = 44100):
        riva = _require_riva()
        self._auth = riva.Auth(uri=server)
        self._service = riva.SpeechSynthesisService(self._auth)
        self.language_code = language_code
        self.voice_name = voice_name
        self.sample_rate_hz = sample_rate_hz

    def synthesize(self, text: str) -> bytes:
        resp = self._service.synthesize(
            text, voice_name=self.voice_name,
            language_code=self.language_code,
            sample_rate_hz=self.sample_rate_hz)
        return resp.audio
