"""On-device batch embedding service."""

from .encoder import EmbeddingService, HashEmbedder, get_embedder

__all__ = ["EmbeddingService", "HashEmbedder", "get_embedder"]
