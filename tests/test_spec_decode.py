"""Speculative decoding (engine/spec_decode.py + the verify round).

The contract this suite pins, layer by layer:

- **Drafting** (host): prompt-lookup n-gram proposals, longest-suffix
  preference, recency, the adaptive-K controller.
- **Verification sampler** (ops/fused_sampler.py): the vocab-tiled
  ``fused_verify_sample`` is verdict-identical to the materialized
  ``verify_reference_tiled`` oracle under fixed keys, and the
  rejection-sampling rule PRESERVES the target distribution — the
  acceptance criterion's "output distribution is unchanged".
- **Engine** exactness: greedy speculative decoding is TOKEN-IDENTICAL
  to the non-speculative engine across chat-shaped (multi-turn, warm
  prefix-cache) and openloop-shaped (concurrent cold burst) mini-runs,
  including a stop word completing mid-burst; ``ENGINE_SPEC_DECODE=0``
  restores the exact plain decode path.
- **Memory**: the verify round's jaxpr never materializes a
  (rows, V) intermediate — the round-8 assertion with verification
  rows enabled.
- **Bench**: the chat scenario's ``spec.tokens_per_step`` clears 1.5 on
  the copy-heavy CPU mix, and the schema-validated ``spec`` block is
  emitted.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from generativeaiexamples_tpu.engine import Engine, EngineConfig, SamplingParams
from generativeaiexamples_tpu.engine.detokenizer import StopWordTrap
from generativeaiexamples_tpu.engine.scheduler import StepCostModel
from generativeaiexamples_tpu.engine.spec_decode import (
    AdaptiveDraftController, PromptLookupDrafter, SpecConfig, spec_enabled)
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import LlamaConfig
from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer
from generativeaiexamples_tpu.ops.fused_sampler import (
    choose_tile, fused_verify_sample, verify_reference_tiled)
from generativeaiexamples_tpu.ops.sampling import mask_words, pack_mask_np

CFG = LlamaConfig(vocab_size=259 + 5, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  max_position_embeddings=1024)


def make_engine(params, spec: bool, **kw):
    base = dict(max_slots=4, max_input_length=96, max_output_length=32,
                prefill_buckets=(16, 32, 96), page_size=16,
                dtype="float32", max_queue=64, spec_decode=spec)
    base.update(kw)
    return Engine(params, CFG, ByteTokenizer(), EngineConfig(**base))


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.key(7), dtype=jnp.float32)


# ------------------------------------------------------------- drafter


def test_drafter_proposes_continuation_of_last_match():
    d = PromptLookupDrafter([1, 2, 3, 9, 9, 1, 2, 3, 7, 8, 1, 2, 3],
                            ngram_max=3, ngram_min=1)
    # suffix trigram (1,2,3) last occurred earlier at index 5 -> 7, 8
    assert d.propose(2) == [7, 8]
    assert d.propose(5) == [7, 8, 1, 2, 3]   # continuation clipped to k


def test_drafter_prefers_longest_ngram():
    # suffix (5, 6): bigram match at 1 -> continue 7; unigram 6 also
    # occurs at 3 (-> 9) but the longer match must win
    d = PromptLookupDrafter([5, 6, 7, 6, 9, 5, 6], ngram_max=3,
                            ngram_min=1)
    assert d.propose(1) == [7]


def test_drafter_no_match_returns_empty():
    d = PromptLookupDrafter([1, 2, 3, 4, 5], ngram_max=3, ngram_min=1)
    assert d.propose(4) == []
    assert d.propose(0) == []


def test_drafter_recency_and_incremental_extend():
    d = PromptLookupDrafter([4, 1, 7, 4, 1, 8], ngram_max=2, ngram_min=1)
    d.extend([4, 1])
    # most RECENT earlier occurrence of (4, 1) is index 3 -> 8
    assert d.propose(1) == [8]
    # constant run: the longest suffix n-gram matches one position back,
    # so the continuation is the run's next token
    d2 = PromptLookupDrafter([9, 9, 9], ngram_max=3, ngram_min=1)
    assert d2.propose(2) == [9]


def test_adaptive_controller_grows_and_shrinks():
    spec = SpecConfig(max_draft_tokens=8, min_draft_tokens=1)
    ctrl = AdaptiveDraftController(spec)
    assert ctrl.k == 8
    ctrl.update(8, 1)          # 12.5% acceptance -> halve
    assert ctrl.k == 4
    ctrl.update(4, 0)
    ctrl.update(2, 0)
    ctrl.update(1, 0)
    assert ctrl.k == 1         # floored at min
    for _ in range(10):
        ctrl.update(1, 1)      # perfect acceptance -> +1 per round
    assert ctrl.k == 8         # capped at max
    pinned = AdaptiveDraftController(
        SpecConfig(max_draft_tokens=6, adapt=False))
    pinned.update(6, 0)
    assert pinned.k == 6       # SPEC_ADAPT=0 pins K


def test_spec_enabled_env_precedence(monkeypatch):
    monkeypatch.delenv("ENGINE_SPEC_DECODE", raising=False)
    assert spec_enabled(True) and not spec_enabled(False)
    monkeypatch.setenv("ENGINE_SPEC_DECODE", "0")
    assert not spec_enabled(True)
    monkeypatch.setenv("ENGINE_SPEC_DECODE", "1")
    assert spec_enabled(False)


# ------------------------------------------- verification sampler (ops)


def test_fused_verify_matches_reference_oracle():
    """Fixed-key verdict exactness: the tiled verify sampler and the
    materialized oracle agree on every accept decision AND every
    resample token, across greedy/sampled rows, truncations, drafts
    in/out of the kept set, and no-draft (-1) bonus rows."""
    V, R = 256, 16
    tile = choose_tile(V, 64)
    rng = np.random.RandomState(0)
    for trial in range(8):
        logits = jnp.asarray(rng.randn(R, V).astype(np.float32) * 3)
        temp = jnp.asarray(rng.choice([0.0, 0.7, 1.0], R).astype(np.float32))
        top_k = jnp.asarray(rng.choice([0, 1, 5, 40], R).astype(np.int32))
        top_p = jnp.asarray(rng.choice([1.0, 0.9, 0.5], R).astype(np.float32))
        draft = rng.randint(-1, V, size=R).astype(np.int32)
        draft[:4] = np.asarray(jnp.argmax(logits[:4], -1))  # likely accepts
        draft = jnp.asarray(draft)
        seen = np.zeros((R, V), bool)
        seen[rng.rand(R, V) < 0.05] = True
        key = jax.random.key(trial)
        u = jax.random.uniform(jax.random.fold_in(key, 999), (R,))
        acc_f, out_f = fused_verify_sample(
            lambda t0, t: jax.lax.dynamic_slice_in_dim(logits, t0, t,
                                                       axis=1),
            V, key=key, u=u, temp=temp, top_k=top_k, top_p=top_p,
            rep_pen=jnp.ones((R,), jnp.float32),
            seen_words=jnp.asarray(pack_mask_np(seen)),
            banned_words=jnp.zeros((R, mask_words(V)), jnp.uint32),
            draft_ids=draft, tile=tile, cand_k=64)
        acc_r, out_r = verify_reference_tiled(logits, key, u, temp, top_k,
                                              top_p, draft, tile)
        np.testing.assert_array_equal(np.asarray(acc_f), np.asarray(acc_r))
        np.testing.assert_array_equal(np.asarray(out_f), np.asarray(out_r))


@pytest.mark.parametrize("top_k,top_p", [(0, 1.0), (8, 1.0), (0, 0.7)])
def test_rejection_sampling_preserves_distribution(top_k, top_p):
    """Distribution preservation (fixed key, batched): accept-with-p(d)
    then resample-from-residual must leave the emitted token's marginal
    equal to the target truncated softmax — acceptance rate == p(draft)
    and total-variation distance at sampling-noise level."""
    V, N = 64, 4000
    tile = choose_tile(V, 32)
    base = np.random.RandomState(1).randn(V).astype(np.float32) * 2
    logits = jnp.asarray(np.tile(base, (N, 1)))
    # target distribution under the same truncation rule
    scaled = base / 0.8
    order = np.argsort(-scaled)
    probs = np.exp(scaled - scaled.max())
    probs /= probs.sum()
    kk = top_k if top_k > 0 else V
    sp = probs[order]
    cum = np.cumsum(sp)
    keeps = (cum - sp) < (top_p if 0 < top_p < 1 else 1.0)
    keep = np.zeros(V, bool)
    for r, idx in enumerate(order):
        keep[idx] = r < kk and keeps[r]
    target = np.where(keep, probs, 0)
    target /= target.sum()
    draft = int(order[1])     # a likely-but-not-top token
    key = jax.random.key(42)
    u = jax.random.uniform(jax.random.fold_in(key, 999), (N,))
    acc, out = fused_verify_sample(
        lambda t0, t: jax.lax.dynamic_slice_in_dim(logits, t0, t, axis=1),
        V, key=key, u=u, temp=jnp.full((N,), 0.8),
        top_k=jnp.full((N,), top_k, jnp.int32),
        top_p=jnp.full((N,), top_p, jnp.float32),
        rep_pen=jnp.ones((N,), jnp.float32),
        seen_words=jnp.zeros((N, mask_words(V)), jnp.uint32),
        banned_words=jnp.zeros((N, mask_words(V)), jnp.uint32),
        draft_ids=jnp.full((N,), draft, jnp.int32), tile=tile, cand_k=64)
    emitted = np.where(np.asarray(acc), draft, np.asarray(out))
    accept_rate = float(np.asarray(acc).mean())
    assert abs(accept_rate - target[draft]) < 0.03
    emp = np.bincount(emitted, minlength=V) / N
    tv = 0.5 * np.abs(emp - target).sum()
    assert tv < 0.06, f"TV distance {tv} — distribution not preserved"


def test_verify_rejected_draft_never_reemitted_in_truncated_mode():
    """With a point-mass proposal the residual excludes the draft: a
    rejected draft must not come back as the resample (unless the kept
    set is exactly {draft}, where p=1 makes rejection impossible)."""
    V, N = 64, 512
    tile = choose_tile(V, 32)
    base = np.random.RandomState(3).randn(V).astype(np.float32)
    logits = jnp.asarray(np.tile(base, (N, 1)))
    draft = int(np.argsort(-base)[2])
    key = jax.random.key(9)
    u = jax.random.uniform(jax.random.fold_in(key, 999), (N,))
    acc, out = fused_verify_sample(
        lambda t0, t: jax.lax.dynamic_slice_in_dim(logits, t0, t, axis=1),
        V, key=key, u=u, temp=jnp.ones((N,)),
        top_k=jnp.full((N,), 8, jnp.int32), top_p=jnp.ones((N,)),
        rep_pen=jnp.ones((N,), jnp.float32),
        seen_words=jnp.zeros((N, mask_words(V)), jnp.uint32),
        banned_words=jnp.zeros((N, mask_words(V)), jnp.uint32),
        draft_ids=jnp.full((N,), draft, jnp.int32), tile=tile, cand_k=64)
    rejected = ~np.asarray(acc)
    assert rejected.any()
    assert not (np.asarray(out)[rejected] == draft).any()


# ------------------------------------------------- engine-level parity


def _greedy_burst(eng, prompts, max_tokens=20, stop_words=None):
    sp = SamplingParams(max_tokens=max_tokens, top_k=1, ignore_eos=True,
                        stop_words=stop_words or [])
    streams = [eng.submit(list(p), sp) for p in prompts]
    return [(s.text(), list(s.token_ids), s.finish_reason)
            for s in streams]


def test_greedy_spec_token_identical_openloop_burst(params):
    """Openloop-shaped mini-run: a concurrent burst of unique cold
    prompts (more requests than slots) must be token-identical with
    speculation on — drafts that verify wrong are corrected exactly."""
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(4, 200, size=n))
               for n in (24, 11, 17, 30, 9, 21)]
    with make_engine(params, spec=False) as eng:
        base = _greedy_burst(eng, prompts)
    with make_engine(params, spec=True) as eng:
        spec = _greedy_burst(eng, prompts)
        stats = eng.stats
    assert base == spec
    assert stats["spec_verify_rounds"] > 0, "speculation never engaged"
    assert stats["spec_draft_tokens"] > 0


def test_greedy_spec_token_identical_chat_warm_prefix(params):
    """Chat-shaped mini-run: multi-turn history re-submission, so turn
    2+ admits through the prefix cache (warm start) — the verify path
    must stay token-identical on top of cache-seeded slots."""
    results = {}
    for spec in (False, True):
        with make_engine(params, spec=spec) as eng:
            history = [(7 * i) % 200 + 4 for i in range(48)]
            turns = []
            for t in range(3):
                prompt = (history + [11 + t, 12, 13])[-90:]
                s = eng.submit(prompt, SamplingParams(
                    max_tokens=10, top_k=1, ignore_eos=True))
                s.text()
                turns.append(list(s.token_ids))
                history = prompt + s.token_ids
            results[spec] = turns
            hits = eng.stats["prefix_cache_hit_tokens"]
    assert hits > 0, "scenario never warmed the prefix cache"
    assert results[False] == results[True]


def test_stop_word_mid_burst_truncates_exactly(params):
    """A stop word completing mid-burst: the stream must end exactly
    where the non-speculative engine ends it — same text (nothing past
    the stop), same token ids (trailing device-accepted tokens
    discarded), same finish reason — and the slot/pages must be free
    afterwards."""
    tok = ByteTokenizer()
    prompt = tok.encode("stop test")
    with make_engine(params, spec=False) as eng:
        free = eng.submit(prompt, SamplingParams(
            max_tokens=16, top_k=1, ignore_eos=True))
        full_text = free.text()
    assert len(full_text) >= 3, "scenario needs visible text"
    stop = full_text[2]
    out = {}
    for spec in (False, True):
        with make_engine(params, spec=spec) as eng:
            s = eng.submit(prompt, SamplingParams(
                max_tokens=16, top_k=1, ignore_eos=True,
                stop_words=[stop]))
            out[spec] = (s.text(), list(s.token_ids), s.finish_reason)
            if spec:
                # retirement is the scheduler's half of completion and
                # runs after the stream's sentinel — poll for it, then
                # assert the slot and its pages actually came back
                import time as _t
                deadline = _t.monotonic() + 10
                while eng._slots and _t.monotonic() < deadline:
                    _t.sleep(0.01)
                assert not eng._slots
                assert len(eng._free_slots) == eng.cfg.max_slots
    assert out[True][2] == "stop"
    assert stop not in out[True][0]
    assert out[False] == out[True]


def test_env_zero_restores_plain_decode_path(params, monkeypatch):
    """ENGINE_SPEC_DECODE=0 beats spec_decode=True: no drafter state, no
    verify rounds, token-identical output — the engine-level parity
    escape hatch the acceptance criteria pin."""
    prompt = [9, 10, 11, 12] * 6
    with make_engine(params, spec=False) as eng:
        base = eng.submit(prompt, SamplingParams(
            max_tokens=12, top_k=1, ignore_eos=True))
        base.text()
    monkeypatch.setenv("ENGINE_SPEC_DECODE", "0")
    with make_engine(params, spec=True) as eng:
        assert eng._spec is None
        s = eng.submit(prompt, SamplingParams(
            max_tokens=12, top_k=1, ignore_eos=True))
        s.text()
        stats = eng.stats
    assert stats["spec_verify_rounds"] == 0
    assert stats["spec_draft_tokens"] == 0
    assert s.token_ids == base.token_ids


def test_nondraftable_workload_keeps_pipelined_classic_rounds(params):
    """Spec on + a workload with no self-repetition: every round falls
    back to the classic program — token-identical to spec-off, zero
    verify rounds — and the planner's draftable HINT stays False, so
    dispatch-ahead is allowed while rounds are in flight (enabling
    spec on a non-copy workload must cost nothing)."""
    # strictly non-repeating token sequence: no n-gram ever recurs
    prompt = list(range(4, 4 + 40))
    with make_engine(params, spec=False) as eng:
        base = eng.submit(prompt, SamplingParams(
            max_tokens=12, top_k=1, ignore_eos=True))
        base.text()
    with make_engine(params, spec=True) as eng:
        s = eng.submit(prompt, SamplingParams(
            max_tokens=12, top_k=1, ignore_eos=True))
        s.text()
        stats = eng.stats
        # the draftable hint drives the pipeline-vs-drain decision:
        # non-repeating context -> False (pipelined classic rounds),
        # repeating context -> True (hold for a verify round)
        from types import SimpleNamespace as NS

        def fake(ctx):
            return NS(drafter=PromptLookupDrafter(ctx, ngram_max=3,
                                                  ngram_min=1),
                      spec_ctrl=AdaptiveDraftController(eng._spec),
                      eff_max=32, generated=1,
                      stream=NS(token_ids=list(ctx[-1:])))
        assert eng._any_draftable([fake(list(range(4, 40)))]) is False
        assert eng._any_draftable([fake([7, 8, 9] * 5)]) is True
    # generated tokens MAY repeat (model's choice) and then verify
    # rounds legitimately run; but with this model/prompt the output
    # must simply match spec-off whatever path each round took
    assert s.token_ids == base.token_ids
    with make_engine(params, spec=True) as eng:
        # and a repetitive workload still verifies under the hint-gated
        # policy (long enough that the drain + draft opportunity comes)
        a = eng.submit([9, 10, 11, 12] * 8, SamplingParams(
            max_tokens=24, top_k=1, ignore_eos=True))
        a.text()
        assert eng.stats["spec_verify_rounds"] > 0


def test_sampling_spec_runs_and_respects_length(params):
    """Temperature>0 through the verify path: mechanical soundness
    (exact distribution preservation is pinned at the sampler layer) —
    requested lengths honored, mixed greedy/sampled batch fine."""
    with make_engine(params, spec=True) as eng:
        # the sampled request rides verify rounds triggered by the
        # greedy batch-mate's repetitive (hint-positive) context
        a = eng.submit([9, 10, 11, 12] * 8, SamplingParams(
            max_tokens=20, temperature=0.7, top_k=8, top_p=0.9,
            ignore_eos=True))
        b = eng.submit([9, 10, 11, 12] * 8, SamplingParams(
            max_tokens=24, top_k=1, ignore_eos=True))
        a.text(), b.text()
        stats = eng.stats
    assert len(a.token_ids) == 20 and len(b.token_ids) == 24
    assert stats["spec_verify_rounds"] > 0


def test_spec_stats_and_flight_events(params):
    """Observability satellite: the spec counters move, the derived
    acceptance-rate / tokens-per-step gauges agree with the raw ones,
    and per-round draft/accept counts + the engine_verify stage land on
    the request's flight timeline."""
    from generativeaiexamples_tpu.obs import flight as obs_flight

    with make_engine(params, spec=True) as eng:
        rec = obs_flight.FlightRecorder()
        eng.flight = rec
        s = eng.submit([9, 10, 11, 12] * 8, SamplingParams(
            max_tokens=24, top_k=1, ignore_eos=True))
        s.text()
        stats = eng.stats
        tl = rec.find(s.request_id)
    assert stats["spec_verify_rounds"] > 0
    assert stats["spec_verify_tokens"] >= stats["spec_verify_slot_steps"]
    if stats["spec_draft_tokens"]:
        assert stats["spec_acceptance_rate"] == round(
            stats["spec_accepted_tokens"] / stats["spec_draft_tokens"], 4)
    assert stats["spec_tokens_per_step"] == round(
        stats["spec_verify_tokens"] / stats["spec_verify_slot_steps"], 4)
    names = [e[2] for e in tl.events_snapshot()]
    assert "spec_drafted" in names and "spec_accepted" in names
    assert "engine_verify" in names


def test_verify_cost_priced_against_budget(params):
    """Scheduler satellite: verify rounds charge sched_decode_tokens
    through StepCostModel.verify_cost_tokens (not steps x slots), and
    the cost model's ratio pricing behaves."""
    cost = StepCostModel(prefill_ms_per_token=0.1, verify_ms_per_token=0.2)
    assert cost.verify_cost_tokens(10) == 20    # 2x prefill-token price
    assert StepCostModel().verify_cost_tokens(10) == 10   # unmeasured 1:1
    assert cost.verify_cost_tokens(0) == 0
    with make_engine(params, spec=True) as eng:
        s = eng.submit([9, 10, 11, 12] * 8, SamplingParams(
            max_tokens=20, top_k=1, ignore_eos=True))
        s.text()
        stats = eng.stats
    assert stats["spec_verify_rounds"] > 0
    assert stats["sched_decode_tokens"] > 0


# -------------------------------------------------- memory proof (r8)


def _jaxprs_in(val):
    if isinstance(val, jax.core.ClosedJaxpr):
        yield val.jaxpr
    elif isinstance(val, jax.core.Jaxpr):
        yield val
    elif isinstance(val, (list, tuple)):
        for v in val:
            yield from _jaxprs_in(v)


def _walk_avals(jaxpr, out):
    for eqn in jaxpr.eqns:
        out.extend(v.aval for v in eqn.outvars)
        for val in eqn.params.values():
            for sub in _jaxprs_in(val):
                _walk_avals(sub, out)


def test_verify_round_never_materializes_vocab(monkeypatch):
    """The round-8 memory contract WITH verification rows: trace the
    engine's actual fused verify round (sampling variant — the
    stricter one: rejection probabilities, residual samples and
    candidate carries all in play) and assert no intermediate anywhere
    in the jaxpr carries a full (rows, V) array."""
    vocab = 288                                   # 9 mask words, 3 tiles
    monkeypatch.setenv("SAMPLER_TILE", "96")
    monkeypatch.setenv("SAMPLER_CAND_K", "16")
    cfg = LlamaConfig(vocab_size=vocab, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16,
                      max_position_embeddings=256)
    params = llama.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    eng = Engine(params, cfg, ByteTokenizer(), EngineConfig(
        max_slots=4, max_input_length=64, max_output_length=32,
        prefill_buckets=(16, 32, 64), dtype="float32", max_queue=8,
        spec_decode=True, spec_max_draft_tokens=3))
    try:
        assert eng._fused_tail and eng._spec is not None
        ba = 2
        S = eng._spec_S
        fn = eng._make_verify(eng._windows[0], False, ba)
        jaxpr = jax.make_jaxpr(fn)(
            eng.params, eng._state, jax.random.key(1),
            jnp.zeros((ba,), jnp.int32),
            jnp.zeros((eng.cfg.max_slots, S - 1), jnp.int32),
            jnp.zeros((eng.cfg.max_slots,), jnp.int32)).jaxpr
        avals = []
        _walk_avals(jaxpr, avals)
        offenders = [a for a in avals
                     if getattr(a, "ndim", 0) >= 2
                     and a.shape[-1] == vocab]
        assert not offenders, (
            f"verify round materializes vocab-wide intermediates: "
            f"{[(a.shape, str(a.dtype)) for a in offenders]}")
        assert any(getattr(a, "ndim", 0) >= 2 and a.shape[-1] == 96
                   for a in avals), "expected (rows, tile) intermediates"
    finally:
        eng.stop()


# ------------------------------------------------------- StopWordTrap


def test_stopwordtrap_earliest_stop_wins_in_burst():
    """Multi-token bursts deliver several tokens' text in one feed: the
    trap must truncate at the EARLIEST stop occurrence in the text, not
    at the first stop word in list order (the pre-round-9 latent bug),
    and stay silent once tripped."""
    trap = StopWordTrap(["zz", "b"])
    assert trap.feed("a b c zz d") == "a "
    assert trap.stopped
    assert trap.feed("more") == ""
    assert trap.flush() == ""
    # single-feed burst where the LIST-first stop sits later in the text
    trap2 = StopWordTrap(["late", "x"])
    assert trap2.feed("01x23late") == "01"
    # back-compat alias still importable
    from generativeaiexamples_tpu.engine.detokenizer import StopChecker
    assert StopChecker is StopWordTrap


# ---------------------------------------------------------- bench smoke


def test_chat_bench_spec_tokens_per_step(params_key0=None):
    """Acceptance criterion: the chat scenario (copy-heavy prompt mix —
    growing shared history, greedy replies that cycle) reports
    spec.tokens_per_step > 1.5 on CPU with speculation on, and the
    block validates against the bench schema."""
    import bench
    from tools.check_bench_schema import load_schema

    cfg = LlamaConfig(vocab_size=259 + 5, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16,
                      max_position_embeddings=1024)
    params = llama.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    eng = Engine(params, cfg, ByteTokenizer(), EngineConfig(
        max_slots=4, max_input_length=640, max_output_length=64,
        prefill_buckets=(64, 128, 256, 640), page_size=32,
        dtype="float32", max_queue=64, spec_decode=True))
    try:
        chat = bench.run_chat_bench(eng, n_turns=4, system_len=96,
                                    user_len=24, reply_len=48,
                                    warmup=False)
    finally:
        eng.stop()
    spec = chat["spec"]
    assert spec is not None and spec["verify_rounds"] > 0
    assert set(spec) == set(load_schema()["spec"])
    assert spec["tokens_per_step"] > 1.5, (
        f"speculative multiplier too low on the copy-heavy chat mix: "
        f"{spec}")
