"""Connector-class tests against a served fixture (reference behavior:
integrations/langchain/llms/triton_trt_llm.py — LLM subclass streaming
through the serving endpoint; embeddings with passage/query modes)."""

import asyncio
import threading

import jax
import jax.numpy as jnp
import pytest
from aiohttp import web

from generativeaiexamples_tpu.engine import Engine, EngineConfig
from generativeaiexamples_tpu.integrations.langchain_tpu import (
    STOP_WORDS, TpuEmbeddings, TpuLLM)
from generativeaiexamples_tpu.integrations.llamaindex_tpu import (
    TpuLlamaIndexEmbedding, TpuLlamaIndexLLM)
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import LLAMA_TINY
from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer
from generativeaiexamples_tpu.serving.grpc_server import serve_grpc
from generativeaiexamples_tpu.serving.model_server import create_server_app


@pytest.fixture(scope="module")
def served():
    """One engine behind both transports: gRPC + the OpenAI/triton HTTP
    app."""
    params = llama.init_params(LLAMA_TINY, jax.random.key(0),
                               dtype=jnp.float32)
    cfg = EngineConfig(max_slots=2, max_input_length=256,
                       max_output_length=64, prefill_buckets=(32, 64, 256),
                       dtype="float32", page_size=16, kv_pool_tokens=None,
                       steps_per_round=4, dispatch_depth=1)
    engine = Engine(params, LLAMA_TINY, ByteTokenizer(), cfg)
    from generativeaiexamples_tpu.embed.encoder import get_embedder
    embedder = get_embedder("hash", "hash", dim=32)

    grpc_server = serve_grpc(engine, "llama-tiny", embedder, max_output=64,
                             host="127.0.0.1", port=0)

    app = create_server_app(engine, embedder, "llama-tiny")
    loop = asyncio.new_event_loop()
    holder = {}
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)

        async def boot():
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            holder["port"] = site._server.sockets[0].getsockname()[1]
        loop.run_until_complete(boot())
        started.set()
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    started.wait(timeout=30)
    yield {"grpc": f"127.0.0.1:{grpc_server._bound_port}",
           "http": f"http://127.0.0.1:{holder['port']}"}
    loop.call_soon_threadsafe(loop.stop)
    grpc_server.stop(grace=None)
    engine.stop()


@pytest.mark.parametrize("mode", ["grpc", "http"])
def test_tpu_llm_call_and_stream(served, mode):
    llm = TpuLLM(server_url=served[mode], mode=mode, tokens=8)
    full = llm._call("integration prompt", stop=[])
    assert isinstance(full, str) and full
    chunks = [c.text for c in llm._stream("integration prompt", stop=[])]
    assert "".join(chunks) == full


def test_tpu_llm_invoke_contract(served):
    llm = TpuLLM(server_url=served["grpc"], mode="grpc", tokens=8)
    assert llm.invoke("contract check", stop=[]) == \
        llm._call("contract check", stop=[])
    assert llm._llm_type == "tpu_llm"
    assert llm._identifying_params["model_name"] == "ensemble"


def test_tpu_llm_default_stop_words(served):
    """No explicit stop -> the reference's </s> default applies."""
    llm = TpuLLM(server_url=served["grpc"], mode="grpc", tokens=8)
    assert STOP_WORDS == ["</s>"]
    assert isinstance(llm._call("stops"), str)


@pytest.mark.parametrize("mode", ["grpc", "http"])
def test_tpu_embeddings(served, mode):
    emb = TpuEmbeddings(server_url=served[mode], mode=mode)
    docs = emb.embed_documents(["alpha doc", "beta doc"])
    assert len(docs) == 2 and len(docs[0]) == 32
    q = emb.embed_query("alpha doc")
    assert len(q) == 32
    # ranking sanity: the query is closest to its own doc
    import numpy as np
    sims = [float(np.dot(q, d)) for d in docs]
    assert sims[0] > sims[1]


def test_llamaindex_llm(served):
    llm = TpuLlamaIndexLLM(server_url=served["grpc"], mode="grpc", tokens=8)
    resp = llm.complete("llamaindex check")
    assert resp.text
    acc = list(llm.stream_complete("llamaindex check"))
    assert acc[-1].text == resp.text
    assert llm.metadata.context_window == 3000


def test_llamaindex_embedding(served):
    emb = TpuLlamaIndexEmbedding(server_url=served["grpc"], mode="grpc")
    v = emb.get_query_embedding("hello")
    assert len(v) == 32
    t = emb.get_text_embedding("hello")
    assert len(t) == 32
