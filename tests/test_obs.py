"""Observability tests: metrics registry, request timing, tracing no-ops."""

import os
import time

from generativeaiexamples_tpu.obs.metrics import (Registry, RequestTimer)
from generativeaiexamples_tpu.obs import tracing


def test_counter_and_gauge():
    reg = Registry()
    reg.counter("reqs").inc()
    reg.counter("reqs").inc(2)
    reg.gauge("temp").set(3.5)
    snap = reg.snapshot()
    assert snap["reqs"] == 3
    assert snap["temp"] == 3.5


def test_histogram_percentile_and_render():
    reg = Registry()
    h = reg.histogram("lat")
    for v in [0.01, 0.02, 0.05, 0.1, 0.5]:
        h.observe(v)
    assert h.count == 5
    assert 0.0 < h.percentile(0.5) <= 0.1
    text = reg.render_prometheus()
    assert "# TYPE lat histogram" in text
    assert 'lat_bucket{le="+Inf"} 5' in text
    assert "lat_count 5" in text


def test_request_timer_ttft_and_tps():
    reg = Registry()
    t = RequestTimer("gen", registry=reg)
    time.sleep(0.01)
    t.token(5)
    t.token(5)
    t.finish()
    snap = reg.snapshot()
    assert snap["gen_requests_total"] == 1
    assert snap["gen_ttft_seconds_count"] == 1
    assert snap["gen_tokens_total"] == 10
    assert snap["gen_last_tokens_per_second"] > 0
    # Tokens/sec is ALSO a histogram: the distribution survives
    # concurrent requests, unlike the last-write-wins gauge above.
    assert snap["gen_tokens_per_second_count"] == 1
    assert snap["gen_tokens_per_second_sum"] > 0


def test_labeled_counter_and_histogram_render():
    """Label support: children per label-value tuple, rendered as
    name{label="value"} rows (histograms get the label next to le)."""
    reg = Registry()
    c = reg.counter("hits", labelnames=("route",))
    c.labels("generate").inc(2)
    c.labels(route="search").inc()
    h = reg.histogram("stage_seconds", labelnames=("stage",))
    h.labels("prefill").observe(0.03)
    h.labels("prefill").observe(0.3)
    h.labels("decode").observe(0.1)
    text = reg.render_prometheus()
    assert 'hits{route="generate"} 2.0' in text
    assert 'hits{route="search"} 1.0' in text
    assert 'stage_seconds_bucket{stage="prefill",le="+Inf"} 2' in text
    assert 'stage_seconds_count{stage="decode"} 1' in text
    snap = reg.snapshot()
    assert snap['hits{route="generate"}'] == 2.0
    assert snap['stage_seconds_count{stage="prefill"}'] == 2.0
    # a labeled parent cannot be used as a scalar
    import pytest
    with pytest.raises(ValueError):
        c.inc()
    with pytest.raises(ValueError):
        h.observe(0.1)
    with pytest.raises(ValueError):
        c.labels("a", "b")
    # re-registration with different labels is a loud conflict
    with pytest.raises(ValueError):
        reg.counter("hits", labelnames=("other",))


def test_observe_stage_feeds_labeled_histogram():
    from generativeaiexamples_tpu.obs.metrics import observe_stage

    reg = Registry()
    observe_stage("engine_admit_dispatch", 0.004, registry=reg)
    observe_stage("engine_admit_dispatch", 0.008, registry=reg)
    observe_stage("retrieve", 0.001, registry=reg)
    snap = reg.snapshot()
    assert snap[
        'engine_stage_seconds_count{stage="engine_admit_dispatch"}'] == 2.0
    assert snap['engine_stage_seconds_count{stage="retrieve"}'] == 1.0


def test_histogram_concurrent_observe_while_render():
    """Torn-read regression (round-7 satellite): scrapes copy histogram
    state under the histogram's lock, so the rendered cumulative bucket
    counts can never disagree with _count. Hammer observe() from
    threads while rendering and check the monotonic-bucket invariant on
    every scrape."""
    import re
    import threading

    reg = Registry()
    h = reg.histogram("lat", buckets=(0.1, 0.2, 0.4, 0.8))
    stop = threading.Event()

    def worker(seed: int):
        v = 0.05 * (1 + seed)
        while not stop.is_set():
            h.observe(v)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(200):
            text = reg.render_prometheus()
            buckets = [int(m) for m in re.findall(
                r'lat_bucket\{le="[^"]+"\} (\d+)', text)]
            count = int(re.search(r"lat_count (\d+)", text).group(1))
            # cumulative buckets must be nondecreasing and end at _count
            assert buckets == sorted(buckets), buckets
            assert buckets[-1] == count, (buckets, count)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)


def test_tracing_disabled_noops():
    assert not tracing.enabled()
    with tracing.server_span("x", headers={"traceparent": "00-abc"}) as span:
        assert span is None
    with tracing.event_span("retrieve", top_k=4) as span:
        assert span is None
    headers = tracing.inject_context({"a": "b"})
    assert headers == {"a": "b"}


def test_set_enabled_overrides_env(monkeypatch):
    """Enablement is evaluated per call (round-7 satellite): set_enabled
    flips tracing at runtime — no module reimport — and every check
    site (enabled / inject_context / _get_tracer) agrees."""
    monkeypatch.delenv("ENABLE_TRACING", raising=False)
    monkeypatch.setattr(tracing, "_enabled_override", None)
    assert not tracing.enabled()
    tracing.set_enabled(True)
    try:
        assert tracing.enabled()
        tracing.set_enabled(False)
        assert not tracing.enabled()
        assert tracing._get_tracer() is None  # no spans after disable
        assert tracing.inject_context({"a": "b"}) == {"a": "b"}
        # None restores the env check — now honoring a live env change,
        # which the old import-frozen _ENABLED could not see
        tracing.set_enabled(None)
        monkeypatch.setenv("ENABLE_TRACING", "1")
        assert tracing.enabled()
        monkeypatch.delenv("ENABLE_TRACING")
        assert not tracing.enabled()
    finally:
        tracing.set_enabled(None)


def test_instrumented_passthrough():
    import asyncio

    @tracing.instrumented("handler")
    async def handler(request):
        return "ok"

    class FakeReq:
        headers = {}
        rel_url = "/x"

    assert asyncio.new_event_loop().run_until_complete(handler(FakeReq())) == "ok"


def test_traced_rag_request_emits_child_spans(monkeypatch):
    """End-to-end: a traced rag_chain request produces the retrieve /
    templating / llm / embedding child spans (the LlamaIndex-callback
    bridge behavior of the reference, opentelemetry_callback.py:84-197).
    Only the OTel API is installed here, so a fake tracer captures the
    span tree."""
    from contextlib import contextmanager

    class FakeSpan:
        def __init__(self, name, parent, attributes):
            self.name = name
            self.parent = parent
            self.attributes = dict(attributes or {})

        def set_attribute(self, k, v):
            self.attributes[k] = v

    class FakeTracer:
        def __init__(self):
            self.spans = []
            self._stack = []

        @contextmanager
        def start_as_current_span(self, name, context=None, kind=None,
                                  attributes=None):
            span = FakeSpan(name, self._stack[-1] if self._stack else None,
                            attributes)
            self.spans.append(span)
            self._stack.append(span)
            try:
                yield span
            finally:
                self._stack.pop()

    tracer = FakeTracer()
    monkeypatch.setattr(tracing, "_enabled_override", True)
    monkeypatch.setattr(tracing, "_tracer", tracer)

    from generativeaiexamples_tpu.chains.examples.developer_rag import (
        QAChatbot)
    from generativeaiexamples_tpu.utils.app_config import AppConfig
    from generativeaiexamples_tpu.utils.configuration import from_dict

    cfg = from_dict(AppConfig, {
        "llm": {"model_engine": "echo"},
        "embeddings": {"model_engine": "hash", "dimensions": 64},
        "vector_store": {"name": "exact"},
        "text_splitter": {"chunk_size": 50, "chunk_overlap": 10}})
    ex = QAChatbot(config=cfg)
    import tempfile, os
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "d.txt")
        with open(p, "w") as f:
            f.write("The MXU is a 128x128 systolic array.")
        ex.ingest_docs(p, "d.txt")

    with tracing.server_span("generate_answer") as root:
        assert root is not None
        "".join(ex.rag_chain("What is the MXU?", 32))

    names = [s.name for s in tracer.spans]
    for expected in ("embedding", "retrieve", "templating", "llm",
                     "generate_answer"):
        assert expected in names, names
    spans = {s.name: s for s in tracer.spans}
    assert spans["retrieve"].parent is spans["generate_answer"]
    assert "retrieval.score.0" in spans["retrieve"].attributes


def test_maybe_init_distributed():
    """Single-process jax.distributed bootstrap (multi-host DCN path) in a
    subprocess so the coordinator doesn't pollute this test process."""
    import subprocess
    import sys

    code = (
        "import socket, jax\n"
        "from generativeaiexamples_tpu.parallel.mesh import "
        "maybe_init_distributed\n"
        "assert not maybe_init_distributed()\n"       # no env: no-op
        "s = socket.socket(); s.bind(('127.0.0.1', 0))\n"
        "port = s.getsockname()[1]; s.close()\n"
        "assert maybe_init_distributed(f'127.0.0.1:{port}', 1, 0)\n"
        "assert maybe_init_distributed()\n"           # idempotent
        "assert jax.process_count() == 1\n"
        "print('DIST_OK')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=120,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))))
    assert "DIST_OK" in proc.stdout, proc.stderr[-2000:]


def test_record_engine_stats_mirrors_numeric_stats_as_gauges():
    """Scrape-time engine snapshot for /metrics (chains/server.py): every
    numeric engine stat becomes an engine_* gauge; strings and bools
    (e.g. future flags) are skipped rather than rendered as garbage."""
    from generativeaiexamples_tpu.obs.metrics import record_engine_stats

    reg = Registry()
    record_engine_stats({"requests": 3, "prefix_cache_hit_tokens": 512,
                         "prefix_cache_hit_rate": 0.5,
                         "prefix_cache_evicted_pages": 2,
                         "kind": "paged", "steady": True}, registry=reg)
    snap = reg.snapshot()
    assert snap["engine_requests"] == 3.0
    assert snap["engine_prefix_cache_hit_tokens"] == 512.0
    assert snap["engine_prefix_cache_hit_rate"] == 0.5
    assert "engine_kind" not in snap and "engine_steady" not in snap
    text = reg.render_prometheus()
    assert "engine_prefix_cache_hit_rate 0.5" in text
    assert "engine_prefix_cache_evicted_pages 2" in text


def test_record_engine_stats_pipeline_stage_gauges():
    """The overlapped-pipeline stage counters mirror as engine_* gauges,
    and each cumulative (ms, events) pair derives a per-event _avg gauge
    — the scrape answers 'how long does one round's readback wait'
    without PromQL arithmetic. Zero-event pairs publish no average
    (never a division by zero or a misleading 0)."""
    from generativeaiexamples_tpu.obs.metrics import record_engine_stats

    reg = Registry()
    record_engine_stats({"harvest_wait_ms": 300.0, "harvest_rounds": 3,
                         "first_readback_ms": 50.0, "first_readbacks": 2,
                         "dispatch_queue_depth": 1}, registry=reg)
    snap = reg.snapshot()
    assert snap["engine_harvest_wait_ms"] == 300.0
    assert snap["engine_harvest_rounds"] == 3.0
    assert snap["engine_harvest_wait_ms_avg"] == 100.0
    assert snap["engine_first_readback_ms_avg"] == 25.0
    assert snap["engine_dispatch_queue_depth"] == 1.0

    # no events yet: totals mirror, averages stay absent
    reg2 = Registry()
    record_engine_stats({"harvest_wait_ms": 0.0, "harvest_rounds": 0,
                         "first_readback_ms": 0.0, "first_readbacks": 0},
                        registry=reg2)
    snap2 = reg2.snapshot()
    assert "engine_harvest_wait_ms_avg" not in snap2
    assert "engine_first_readback_ms_avg" not in snap2
