"""Observability: OTel tracing spine + first-party metrics.

Parity with the reference's tracing stack (reference: common/tracing.py,
frontend/frontend/tracing.py, tools/observability/llamaindex/
opentelemetry_callback.py) plus the metrics registry the reference lacks
(SURVEY.md §5: "No first-party metrics registry — a gap to fix").
"""

from . import alerts, flight, history, incidents, metrics, rounds, tracing

__all__ = ["alerts", "flight", "history", "incidents", "metrics",
           "rounds", "tracing"]
