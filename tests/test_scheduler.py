"""Token-budget continuous scheduler (engine/scheduler.py): budget
packing, slack ordering, chunk accounting — plus engine-level proof that
chunked prefill actually interleaves with decode (a long prompt no
longer blocks a concurrent short request's first token) while a
decode-only workload plans exactly the rounds it always got."""

import time

import pytest

import jax
import jax.numpy as jnp

from generativeaiexamples_tpu.engine import Engine, EngineConfig, SamplingParams
from generativeaiexamples_tpu.engine.scheduler import (
    PrefillJob, RoundPlan, StepCostModel, TokenBudgetScheduler,
    derive_round_budget)
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import LlamaConfig
from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer

CFG = LlamaConfig(vocab_size=259 + 5, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  max_position_embeddings=256)

PAGE = 16


def make_sched(budget=64, chunk=None, cost=None, one_shot_cap=64):
    return TokenBudgetScheduler(
        cost or StepCostModel(decode_step_ms=2.0, prefill_ms_per_token=0.25),
        page_size=PAGE, steps_per_round=4, round_budget_tokens=budget,
        chunk_tokens=chunk, max_one_shot_tokens=one_shot_cap)


# --------------------------------------------------------- cost model


def test_cost_model_from_profile_and_default_prefill_estimate():
    m = StepCostModel.from_profile({
        "full_ms_per_step": 3.0, "slots": 4,
        "prefill_ms_per_token": 0.5})
    assert m.decode_step_ms == 3.0 and m.prefill_ms_per_token == 0.5
    # artifacts predating the prefill measurement estimate it from the
    # decode step (per-slot cost / 4x batching efficiency)
    old = StepCostModel.from_profile({"full_ms_per_step": 4.0, "slots": 8})
    assert old.prefill_ms_per_token == pytest.approx(4.0 / 8 / 4)
    assert old.prefill_s(1000) == pytest.approx(0.125)


def test_derive_round_budget_page_quantized_and_floored():
    m = StepCostModel(decode_step_ms=2.0, prefill_ms_per_token=0.25)
    # 4 steps * 2 ms / 0.25 ms per token = 32 tokens -> 2 pages of 16
    assert derive_round_budget(m, 4, PAGE) == 32
    # a pathological model still yields at least one page
    tiny = StepCostModel(decode_step_ms=0.001, prefill_ms_per_token=10.0)
    assert derive_round_budget(tiny, 4, PAGE) == PAGE


def test_load_falls_back_to_defaults(tmp_path, monkeypatch):
    monkeypatch.setenv("SCHED_PROFILE_JSON", str(tmp_path / "missing.json"))
    # unreadable env path falls through to the committed artifact or the
    # defaults — never raises
    m = StepCostModel.load()
    assert m.decode_step_ms > 0 and m.prefill_ms_per_token > 0


# --------------------------------------------------- topology-keyed rows


def test_topology_key_canonicalization():
    from generativeaiexamples_tpu.engine.scheduler import topology_key

    assert topology_key(None) == "tp=1"
    assert topology_key({}) == "tp=1"
    # trivial axes drop; non-trivial ones sort, so one canonical label
    # per mesh shape however the dict was built
    assert topology_key({"dp": 1, "pp": 1, "tp": 2}) == "tp=2"
    assert topology_key({"tp": 2, "sp": 4}) == "sp=4,tp=2"
    assert topology_key({"dp": 1, "sp": 1, "tp": 1}) == "tp=1"


def test_load_matches_topology_row(tmp_path, monkeypatch):
    """Topology precedence (docs/scheduler.md): an artifact's own label
    (absent == tp=1) or a ``topologies`` row matching the engine's mesh
    wins; the row's keys override the shared fields; with no matching
    row anywhere the newest parseable artifact is used as-is."""
    import json

    art = tmp_path / "PROFILE_topo.json"
    art.write_text(json.dumps({
        "full_ms_per_step": 2.0, "prefill_ms_per_token": 0.25,
        "slots": 8,
        "topologies": {"tp=2": {"full_ms_per_step": 1.5,
                                "prefill_ms_per_token": 0.125}},
    }))
    monkeypatch.setenv("SCHED_PROFILE_JSON", str(art))

    single = StepCostModel.load(topology="tp=1")
    assert single.decode_step_ms == 2.0
    assert single.topology == "tp=1"

    tp2 = StepCostModel.load(topology="tp=2")
    assert tp2.decode_step_ms == 1.5
    assert tp2.prefill_ms_per_token == 0.125
    assert tp2.topology == "tp=2"
    assert tp2.source.endswith("@tp=2")
    # the budgets the two rows derive DIFFER — the acceptance-criterion
    # fact the multichip bench pins end-to-end
    assert derive_round_budget(tp2, 4, PAGE) != \
        derive_round_budget(single, 4, PAGE)

    # no matching row: the artifact still beats built-in defaults, and
    # its topology field records the mismatch (tp=1 measurement)
    tp4 = StepCostModel.load(topology="tp=4")
    assert tp4.decode_step_ms == 2.0 and tp4.topology == "tp=1"


def test_load_artifact_own_topology_label(tmp_path, monkeypatch):
    """A --mesh-generated artifact (topology stamped at top level) is
    matched by label; a tp=1 engine skips it in favor of an untagged
    (single-chip) artifact even when the tagged one sorts newer."""
    import json

    (tmp_path / "PROFILE_r98.json").write_text(json.dumps({
        "full_ms_per_step": 3.0, "prefill_ms_per_token": 0.3,
        "slots": 8}))
    (tmp_path / "PROFILE_r99.json").write_text(json.dumps({
        "full_ms_per_step": 1.0, "prefill_ms_per_token": 0.1,
        "slots": 8, "topology": "tp=2"}))
    monkeypatch.chdir(tmp_path)
    import generativeaiexamples_tpu.engine.scheduler as sched
    monkeypatch.setattr(sched, "_REPO_ROOT", str(tmp_path))

    tp2 = StepCostModel.load(topology="tp=2")
    assert tp2.decode_step_ms == 1.0 and tp2.topology == "tp=2"
    single = StepCostModel.load(topology="tp=1")
    assert single.decode_step_ms == 3.0 and single.topology == "tp=1"


# ------------------------------------------------------ budget packing


def test_plan_decode_only_unchanged():
    plan = make_sched().plan_round(decode_steps=4, active_decodes=2)
    assert plan.decode_steps == 4 and not plan.chunks
    assert plan.decode_cost_tokens == 8
    assert not plan.interleaved


def test_plan_respects_budget_and_page_quantizes():
    sched = make_sched(budget=48)
    long_job = PrefillJob(key="long", remaining=200, seq=0, started=True)
    plan = sched.plan_round(decode_steps=0, active_decodes=0,
                            inflight=[long_job])
    # whole leftover, quantized down to whole pages, never over budget
    assert plan.chunks == [("long", 48)]
    assert plan.prefill_tokens <= plan.budget_tokens


def test_plan_decode_cost_shrinks_prefill_share():
    sched = make_sched(budget=64)
    job = PrefillJob(key="j", remaining=500, seq=0, started=True)
    # 4 steps x 2 active slots = 8 token-equivalents of decode cost;
    # the prefill grant shrinks accordingly (56 -> 48 after paging)
    plan = sched.plan_round(decode_steps=4, active_decodes=2,
                            inflight=[job])
    assert plan.decode_cost_tokens == 8
    assert plan.chunks == [("j", 48)]
    assert plan.interleaved


def test_plan_liveness_floor_under_decode_saturation():
    # decode eats the whole budget; a waiting prefill still gets a page
    sched = make_sched(budget=32)
    job = PrefillJob(key="j", remaining=100, seq=0, started=True)
    plan = sched.plan_round(decode_steps=4, active_decodes=32,
                            inflight=[job])
    assert plan.chunks == [("j", PAGE)]


def test_plan_idle_engine_one_shots_a_lone_short_prompt():
    sched = make_sched(budget=PAGE, one_shot_cap=64)
    job = PrefillJob(key="j", remaining=30, seq=0)
    plan = sched.plan_round(decode_steps=0, active_decodes=0, backlog=[job])
    # nothing to protect: the whole prompt goes in one grant even though
    # it exceeds the budget — up to 2x the budget
    assert plan.chunks == [("j", 30)]
    # ...beyond 2x the budget a lone prompt CHUNKS even on an idle
    # engine: a dispatched grant is un-preemptible, so an unbounded
    # one-shot would re-open the prefill wall for the next arrival
    big = PrefillJob(key="b", remaining=60, seq=0)
    plan = sched.plan_round(decode_steps=0, active_decodes=0, backlog=[big])
    assert plan.chunks[0][1] <= 2 * PAGE
    # the bucket cap binds when it is the smaller of the two
    tight = make_sched(budget=64, one_shot_cap=PAGE)
    huge = PrefillJob(key="h", remaining=65, seq=0)
    plan = tight.plan_round(decode_steps=0, active_decodes=0, backlog=[huge])
    assert plan.chunks[0][1] <= PAGE


def test_plan_fair_share_admits_short_behind_long():
    # The acceptance shape: a long in-flight prefill plus a short
    # waiting prompt. Fair-share packing must grant the short its WHOLE
    # prompt this round (it fits the share), not starve it behind the
    # long prefill.
    sched = make_sched(budget=32)
    long_job = PrefillJob(key="long", remaining=100, seq=0, started=True)
    short_job = PrefillJob(key="short", remaining=8, seq=1)
    plan = sched.plan_round(decode_steps=0, active_decodes=0,
                            inflight=[long_job], backlog=[short_job])
    grants = dict(plan.chunks)
    assert grants["short"] == 8          # final grant, sub-page allowed
    assert grants["long"] >= PAGE        # long still progresses
    assert plan.prefill_tokens <= plan.budget_tokens


def test_plan_greedy_second_pass_uses_leftover():
    # one small job + one big job, lots of budget: the big job gets the
    # share AND the leftover the small job didn't need
    sched = make_sched(budget=64)
    big = PrefillJob(key="big", remaining=300, seq=0, started=True)
    small = PrefillJob(key="small", remaining=8, seq=1, started=True)
    plan = sched.plan_round(decode_steps=0, active_decodes=0,
                            inflight=[big, small])
    grants = dict(plan.chunks)
    assert grants["small"] == 8
    assert grants["big"] == 48  # 64 - 8 = 56 -> page-quantized 48


def test_plan_max_new_caps_admissions_to_free_slots():
    """``max_new`` (the engine's free-slot count) bounds how many
    backlog jobs get grants — budget is never split across jobs the
    executor cannot admit, and the slack-ordered FRONT of the backlog
    is what gets through, not arrival order."""
    sched = make_sched(budget=64)
    inflight = PrefillJob(key="busy", remaining=200, seq=0, started=True)
    relaxed = PrefillJob(key="relaxed", remaining=32, seq=1,
                         deadline_t=100.0)
    urgent = PrefillJob(key="urgent", remaining=32, seq=2, deadline_t=1.0)
    plan = sched.plan_round(decode_steps=0, active_decodes=0,
                            inflight=[inflight],
                            backlog=[relaxed, urgent], now=0.0, max_new=1)
    grants = dict(plan.chunks)
    assert "urgent" in grants          # smallest slack wins the slot
    assert "relaxed" not in grants     # no grant for a job with no slot
    # the budget the capped job would have eaten goes to live work
    assert grants["busy"] >= PAGE
    assert plan.prefill_tokens <= plan.budget_tokens


def test_plan_chunk_cap_bounds_single_grant():
    sched = make_sched(budget=64, chunk=PAGE)
    job = PrefillJob(key="j", remaining=500, seq=0, started=True)
    plan = sched.plan_round(decode_steps=0, active_decodes=0,
                            inflight=[job])
    assert plan.chunks == [("j", PAGE)]


def test_plan_chunk_grants_capped_at_prefill_bucket():
    """A grant can never exceed the largest compiled prefill bucket —
    the engine clamps the dispatch there, so a bigger grant would burn
    budget on tokens that never execute."""
    sched = TokenBudgetScheduler(
        StepCostModel(decode_step_ms=2.0, prefill_ms_per_token=0.25),
        page_size=PAGE, steps_per_round=4, round_budget_tokens=256,
        max_one_shot_tokens=64)
    a = PrefillJob(key="a", remaining=500, seq=0, started=True)
    b = PrefillJob(key="b", remaining=500, seq=1, started=True)
    plan = sched.plan_round(decode_steps=0, active_decodes=0,
                            inflight=[a, b])
    grants = dict(plan.chunks)
    assert max(grants.values()) <= 64
    # the budget the cap freed went to the OTHER job, not to waste
    assert grants["a"] + grants["b"] > 64


def test_plan_scarcity_rotation_bounds_single_page_starvation():
    """1-page leftover (the PROFILE-derived default budget on real
    configs) + two jobs: a fixed packing order would hand the same job
    the page every round. Rotation alternates, so the second job's wait
    for its first page is bounded by ~len(jobs) rounds."""
    sched = make_sched(budget=PAGE)
    first_page_owner = []
    for _ in range(4):
        long_job = PrefillJob(key="long", remaining=400, seq=0,
                              started=True)
        short_job = PrefillJob(key="short", remaining=8, seq=1)
        plan = sched.plan_round(decode_steps=0, active_decodes=1,
                                inflight=[long_job], backlog=[short_job])
        assert plan.prefill_tokens >= 8  # liveness floor every round
        first_page_owner.append(plan.chunks[0][0])
    assert "short" in first_page_owner    # the waiter got a round
    assert "long" in first_page_owner     # the long prefill still moves


# ------------------------------------------------------- slack ordering


def test_slack_ordering_deadlines_first_then_arrival():
    sched = make_sched()
    now = 100.0
    relaxed = PrefillJob(key="r", remaining=64, deadline_t=now + 10, seq=0)
    urgent = PrefillJob(key="u", remaining=64, deadline_t=now + 0.1, seq=1)
    nodeadline_a = PrefillJob(key="a", remaining=64, seq=2)
    nodeadline_b = PrefillJob(key="b", remaining=64, seq=3)
    order = [j.key for j in sched.order(
        [nodeadline_b, relaxed, nodeadline_a, urgent], now)]
    assert order == ["u", "r", "a", "b"]


def test_slack_accounts_for_prefill_time():
    # same deadline, different prompt length: the longer prompt has less
    # slack (its prefill eats more of the budget) and goes first
    sched = make_sched(cost=StepCostModel(decode_step_ms=2.0,
                                          prefill_ms_per_token=1.0))
    now = 0.0
    short_p = PrefillJob(key="s", remaining=10, deadline_t=1.0, seq=0)
    long_p = PrefillJob(key="l", remaining=900, deadline_t=1.0, seq=1)
    assert sched.slack_s(long_p, now) < sched.slack_s(short_p, now)
    assert [j.key for j in sched.order([short_p, long_p], now)] == ["l", "s"]


def test_chunk_accounting_with_prefix_cache_hit():
    # a warm request's job carries only the UNCACHED suffix, so its
    # grants (and modeled slack) shrink by the cached prefix
    sched = make_sched(budget=32)
    cold = PrefillJob(key="cold", remaining=64, seq=0, started=True)
    warm = PrefillJob(key="warm", remaining=16, seq=1, started=True)
    plan = sched.plan_round(decode_steps=0, active_decodes=0,
                            inflight=[cold, warm])
    grants = dict(plan.chunks)
    assert grants["warm"] == 16          # the suffix completes this round
    assert grants["cold"] == 16
    assert sched.cost.prefill_s(warm.remaining) < \
        sched.cost.prefill_s(cold.remaining)


# --------------------------------------------------------- engine-level


def _engine(**over):
    cfg = dict(max_slots=2, max_input_length=64, max_output_length=16,
               prefill_buckets=(16, 32, 64), dtype="float32",
               page_size=PAGE, kv_pool_tokens=None, max_queue=64,
               steps_per_round=4)
    cfg.update(over)
    params = llama.init_params(CFG, jax.random.key(3), dtype=jnp.float32)
    return Engine(params, CFG, ByteTokenizer(), EngineConfig(**cfg))


def test_engine_interleaves_short_past_long_prefill():
    """One long + one short prompt submitted together: the short
    request's first token must land BEFORE the long prompt finishes its
    chunked prefill — the prefill wall this PR exists to kill. (Before
    the scheduler, admission ran the long prefill to completion first:
    the long request's first token always beat the short's.)"""
    eng = _engine(sched_round_budget_tokens=32)
    try:
        long_s = eng.submit([5] * 64, SamplingParams(max_tokens=4, top_k=1,
                                                     ignore_eos=True))
        short_s = eng.submit([9] * 8, SamplingParams(max_tokens=4, top_k=1,
                                                     ignore_eos=True))
        eng.start()   # both requests are in the same first round plan
        short_s.text()
        long_s.text()
        assert short_s.first_token_time < long_s.first_token_time
        assert len(short_s.token_ids) == 4 and len(long_s.token_ids) == 4
        stats = eng.stats
        # the long prompt streamed through in >= 2 budget-sized chunks
        assert stats["sched_prefill_tokens"] >= 64 + 8
        assert stats["sched_round_budget_tokens"] == 32
        # decode rounds for the short request ran while the long prompt
        # was still prefilling — the interleaving itself
        assert stats["sched_interleaved_rounds"] >= 1
    finally:
        eng.stop()


def test_engine_chunked_output_matches_one_shot():
    """Forcing tiny chunks must not change WHAT the long prompt
    generates — chunked paged prefill is exact (same math as the
    one-shot bucket, modulo dispatch boundaries)."""
    prompt = [3 + (i % 7) for i in range(64)]
    sp = SamplingParams(max_tokens=6, top_k=1, ignore_eos=True)
    # One engine serves both phases (prefix cache off so the second run
    # really recomputes): a lone prompt on an IDLE engine one-shots even
    # under a tiny budget — the idle fast-path — then a decoding
    # neighbor keeps the engine busy so the resubmission takes the
    # chunked path.
    eng = _engine(sched_round_budget_tokens=PAGE, prefix_cache=False)
    try:
        eng.start()
        one_shot = eng.submit(prompt, sp)
        one_shot.text()
        assert eng.stats["sched_interleaved_rounds"] == 0
        noise = eng.submit([11] * 8, SamplingParams(
            max_tokens=16, top_k=1, ignore_eos=True))
        chunked = eng.submit(prompt, sp)
        chunked.text()
        noise.text()
        assert eng.stats["sched_interleaved_rounds"] >= 1
    finally:
        eng.stop()
    assert chunked.token_ids == one_shot.token_ids


def test_engine_decode_only_rounds_unchanged():
    """No prefill pending: the plan dispatches full steps_per_round
    rounds with a right-sized tail — exactly the pre-scheduler cadence
    (tokens per round unchanged; nothing counted as interleaved)."""
    eng = _engine(max_slots=1, steps_per_round=8)
    try:
        eng.start()
        s = eng.submit([7] * 8, SamplingParams(max_tokens=17, top_k=1,
                                               ignore_eos=True))
        s.text()
        stats = eng.stats
        assert len(s.token_ids) == 17
        # 1 prefill token + 16 decode tokens in rounds of 8
        assert stats["decode_steps"] == 16
        assert stats["harvest_rounds"] == 2
        assert stats["sched_interleaved_rounds"] == 0
        assert stats["sched_decode_tokens"] == 16
    finally:
        eng.stop()


def test_engine_budget_env_override(monkeypatch):
    monkeypatch.setenv("SCHED_ROUND_BUDGET_TOKENS", "48")
    eng = _engine()
    try:
        assert eng._sched.round_budget_tokens == 48
        assert eng.stats["sched_round_budget_tokens"] == 48
    finally:
        eng.stop()


def test_engine_warm_admission_prefills_suffix_only():
    """PR-1 interaction: a prefix-cache hit shrinks the chunk plan — the
    warm admission's granted prefill tokens cover only the uncached
    suffix."""
    eng = _engine(max_slots=1)
    try:
        eng.start()
        prompt = [4 + (i % 9) for i in range(32)]
        sp = SamplingParams(max_tokens=2, top_k=1, ignore_eos=True)
        eng.submit(prompt, sp).text()
        cold_tokens = eng.stats["sched_prefill_tokens"]
        eng.submit(prompt, sp).text()
        warm_tokens = eng.stats["sched_prefill_tokens"] - cold_tokens
        hit = eng.stats["prefix_cache_hit_tokens"]
        assert hit > 0
        assert warm_tokens == len(prompt) - hit
        assert warm_tokens < cold_tokens
    finally:
        eng.stop()


def test_engine_stats_expose_sched_gauges():
    eng = _engine()
    try:
        stats = eng.stats
        for key in ("sched_round_budget_tokens", "sched_prefill_tokens",
                    "sched_decode_tokens", "sched_interleaved_rounds",
                    "sched_prefill_share"):
            assert key in stats
        assert stats["sched_round_budget_tokens"] >= PAGE
        assert stats["sched_prefill_share"] == 0.0
    finally:
        eng.stop()


def test_engine_deadline_sheds_from_reordered_backlog():
    """PR-5 integration: queue-expired requests shed via deadline_queue
    from anywhere in the backlog (not just FIFO head), and deadline'd
    traffic is admitted ahead of earlier-arrived no-deadline traffic."""
    eng = _engine(max_slots=1)
    try:
        # occupy the only slot so later submissions queue
        busy = eng.submit([7] * 8, SamplingParams(max_tokens=16, top_k=1,
                                                  ignore_eos=True))
        eng.start()
        filler = eng.submit([8] * 8, SamplingParams(max_tokens=2, top_k=1,
                                                    ignore_eos=True))
        expired = eng.submit([9] * 8, SamplingParams(max_tokens=2),
                             deadline_t=time.monotonic())  # already past
        assert expired.text() == ""
        assert expired.finish_reason == "deadline_queue"
        busy.text()
        filler.text()
        assert eng.stats["deadline_queue_drops"] == 1
    finally:
        eng.stop()
