"""Operator HA: lease-based leader election + the direct apiserver client.

The reference gets both from controller-runtime (manager leader election,
main.go; client/watch machinery, helmpipeline_controller.go:119-135) and
verifies controllers against envtest's real apiserver
(controllers/suite_test.go:50-60). No kube binaries exist in this image,
so the envtest role is played by a REAL HTTP fake apiserver (aiohttp)
speaking the REST subset ApiServerKube uses — CRUD, status subresource,
resourceVersion 409s, labelSelector lists, ?watch=1 streaming — while
the election protocol races are driven on InMemoryKube's optimistic
concurrency.
"""

import asyncio
import datetime
import json
import threading

import pytest

from generativeaiexamples_tpu.deploy.apiserver import (ApiServerKube,
                                                       resource_path)
from generativeaiexamples_tpu.deploy.kube import (ConflictError,
                                                  InMemoryKube)
from generativeaiexamples_tpu.deploy.leader import LEASE_API, LeaderElector

UTC = datetime.timezone.utc


# ---------------------------------------------------------- leader election

class Clock:
    def __init__(self):
        self.now = datetime.datetime(2026, 1, 1, tzinfo=UTC)

    def __call__(self):
        return self.now

    def tick(self, seconds):
        self.now += datetime.timedelta(seconds=seconds)


def test_leader_acquire_renew_and_block():
    kube = InMemoryKube()
    clock = Clock()
    a = LeaderElector(kube, "a", lease_seconds=15, clock=clock)
    b = LeaderElector(kube, "b", lease_seconds=15, clock=clock)

    assert a.try_acquire() and a.is_leader
    # b cannot take a live lease
    assert not b.try_acquire() and not b.is_leader
    # a renews within the window
    clock.tick(10)
    assert a.try_acquire()
    # still blocked for b (renewal moved the expiry)
    clock.tick(10)
    assert not b.try_acquire()


def test_leader_takeover_after_expiry_counts_transition():
    kube = InMemoryKube()
    clock = Clock()
    a = LeaderElector(kube, "a", lease_seconds=15, clock=clock)
    b = LeaderElector(kube, "b", lease_seconds=15, clock=clock)
    assert a.try_acquire()
    clock.tick(16)  # a's lease expires (crashed holder)
    assert b.try_acquire() and b.is_leader
    lease = kube.get(b.key)
    assert lease["spec"]["holderIdentity"] == "b"
    assert lease["spec"]["leaseTransitions"] == 1
    # a comes back: sees b's live lease, steps down
    assert not a.try_acquire()


def test_leader_takeover_race_one_winner():
    """Two candidates race an expired lease; the optimistic-concurrency
    conflict makes exactly one win."""
    kube = InMemoryKube()
    clock = Clock()
    a = LeaderElector(kube, "a", lease_seconds=15, clock=clock)
    b = LeaderElector(kube, "b", lease_seconds=15, clock=clock)
    c = LeaderElector(kube, "c", lease_seconds=15, clock=clock)
    assert a.try_acquire()
    clock.tick(20)

    # simulate b and c reading the expired lease concurrently: c applies
    # between b's read and write by injecting through the fake
    stale = kube.get(b.key)
    assert b._expired(stale)
    assert c.try_acquire()                       # c wins first
    with pytest.raises(ConflictError):
        kube.apply(b._lease_obj(stale))          # b's write carries stale rv
    assert not b.try_acquire()                   # and candidacy sees c live


def test_leader_release_frees_lease_immediately():
    kube = InMemoryKube()
    clock = Clock()
    a = LeaderElector(kube, "a", lease_seconds=15, clock=clock)
    b = LeaderElector(kube, "b", lease_seconds=15, clock=clock)
    assert a.try_acquire()
    a.release()
    assert not a.is_leader
    # no expiry wait needed: empty holder is acquirable now
    assert b.try_acquire()


def test_leader_run_renews_during_long_cycle():
    """A watch cycle outlives the lease window: the background renewer
    must keep the lease alive so no standby can steal it mid-cycle
    (review catch: without concurrent renewal, every default cycle
    expired the lease and split-brained the reconcilers)."""
    import time as _time

    kube = InMemoryKube()
    a = LeaderElector(kube, "a", lease_seconds=1)       # real clock
    b = LeaderElector(kube, "b", lease_seconds=1)
    cycles = []

    def long_cycle():
        _time.sleep(1.5)                 # longer than the lease window
        cycles.append(b.try_acquire())   # standby probes mid/post cycle

    a.run(long_cycle, renew_seconds=0.2,
          stop=lambda: len(cycles) >= 2)
    # b never acquired while a's renewer was alive
    assert cycles == [False, False]


def test_leader_kubectl_conflict_maps_to_lost_race(monkeypatch):
    """KubectlKube surfaces apiserver optimistic-concurrency failures as
    ConflictError so a lost takeover race returns the elector to
    candidacy instead of crashing the operator (review catch)."""
    import subprocess
    from generativeaiexamples_tpu.deploy.kube import KubectlKube

    def fake_run(cmd, input=None, capture_output=None, text=None,
                 timeout=None):
        return subprocess.CompletedProcess(
            cmd, 1, stdout="",
            stderr='Operation cannot be fulfilled on leases "x": the '
                   'object has been modified')
    monkeypatch.setattr(subprocess, "run", fake_run)
    kube = KubectlKube()
    with pytest.raises(ConflictError):
        kube.apply({"apiVersion": "coordination.k8s.io/v1",
                    "kind": "Lease", "metadata": {"name": "x"}})


def test_apiserver_write_404_raises(monkeypatch):
    """A 404 on a WRITE (missing namespace/collection) must raise, not
    report success; reads still map 404 to None (review catch: the old
    blanket mapping made a deploy into a missing namespace a no-op
    'success')."""
    import io
    from urllib import error as urlerror
    from urllib import request as urlrequest

    def fake_urlopen(req, timeout=None, context=None):
        raise urlerror.HTTPError(req.full_url, 404, "NotFound", {},
                                 io.BytesIO(b'{"reason":"NotFound"}'))
    monkeypatch.setattr(urlrequest, "urlopen", fake_urlopen)
    kube = ApiServerKube(base_url="http://127.0.0.1:1", token="t")
    assert kube.get(("v1", "ConfigMap", "ns", "missing")) is None
    with pytest.raises(RuntimeError, match="404"):
        kube._request("POST", "/api/v1/namespaces/missing/configmaps",
                      body={"kind": "ConfigMap"})


def test_leader_run_gates_callback():
    kube = InMemoryKube()
    clock = Clock()
    a = LeaderElector(kube, "a", lease_seconds=15, clock=clock)
    b = LeaderElector(kube, "b", lease_seconds=15, clock=clock)
    assert a.try_acquire()
    calls = []
    rounds = iter(range(3))

    def work():
        calls.append("b-worked")

    # b never leads while a's lease is live: run() with a stop after a few
    # candidacy attempts must not invoke the callback
    b.run(work, renew_seconds=0, retry_seconds=0,
          stop=lambda: next(rounds, None) is None)
    assert calls == []
    assert not b.is_leader


# ------------------------------------------------------- fake apiserver HTTP

class FakeApiServer:
    """aiohttp fake speaking the REST subset ApiServerKube uses, backed
    by InMemoryKube semantics (including resourceVersion 409s)."""

    def __init__(self):
        self.store = InMemoryKube()
        self.watch_queues: list[asyncio.Queue] = []
        self.loop = None
        self.port = None

    # --- request handling

    def _parse(self, path):
        parts = [p for p in path.split("/") if p]
        # /api/v1/... or /apis/<group>/<ver>/...
        if parts[0] == "api":
            api, rest = parts[1], parts[2:]
        else:
            api, rest = f"{parts[1]}/{parts[2]}", parts[3:]
        ns = None
        if rest and rest[0] == "namespaces":
            ns, rest = rest[1], rest[2:]
        plural = rest[0] if rest else ""
        name = rest[1] if len(rest) > 1 else ""
        sub = rest[2] if len(rest) > 2 else ""
        kind = {"helmpipelines": "HelmPipeline", "leases": "Lease",
                "deployments": "Deployment", "services": "Service",
                "configmaps": "ConfigMap"}.get(
            plural, plural[:-1].capitalize())
        return api, kind, ns, name, sub

    async def handle(self, request):
        from aiohttp import web
        api, kind, ns, name, sub = self._parse(request.path)
        if request.query.get("watch") == "1":
            return await self.serve_watch(request)
        store = self.store
        if request.method == "GET" and name:
            obj = store.get((api, kind, ns or "default", name))
            if obj is None:
                return web.json_response({"reason": "NotFound"}, status=404)
            return web.json_response(obj)
        if request.method == "GET":
            sel = request.query.get("labelSelector", "")
            items = []
            for key, obj in store.objects.items():
                if key[1] != kind:
                    continue
                if ns and key[2] != ns:
                    continue
                if sel:
                    label, _, value = sel.partition("=")
                    if obj.get("metadata", {}).get("labels", {}).get(
                            label) != value:
                        continue
                items.append(obj)
            return web.json_response({"items": items})
        if request.method in ("POST", "PUT"):
            obj = json.loads(await request.text())
            try:
                store.apply(obj)
            except ConflictError as exc:
                return web.json_response({"reason": str(exc)}, status=409)
            stored = store.get(
                (obj.get("apiVersion", api), obj.get("kind", kind),
                 obj.get("metadata", {}).get("namespace", "default"),
                 obj.get("metadata", {}).get("name", "")))
            self.broadcast({"type": "ADDED" if request.method == "POST"
                            else "MODIFIED", "object": stored})
            return web.json_response(stored)
        if request.method == "PATCH" and sub == "status":
            patch = json.loads(await request.text())
            store.update_status((api, kind, ns or "default", name),
                                patch.get("status", {}))
            return web.json_response(
                store.get((api, kind, ns or "default", name)) or {})
        if request.method == "DELETE":
            obj = store.get((api, kind, ns or "default", name))
            existed = store.delete((api, kind, ns or "default", name))
            if not existed:
                return web.json_response({"reason": "NotFound"}, status=404)
            self.broadcast({"type": "DELETED", "object": obj})
            return web.json_response({"status": "Success"})
        return web.json_response({"reason": "bad request"}, status=400)

    def broadcast(self, event):
        for q in list(self.watch_queues):
            self.loop.call_soon_threadsafe(q.put_nowait, event)

    async def serve_watch(self, request):
        from aiohttp import web
        resp = web.StreamResponse()
        resp.content_type = "application/json"
        await resp.prepare(request)
        q: asyncio.Queue = asyncio.Queue()
        self.watch_queues.append(q)
        timeout = float(request.query.get("timeoutSeconds", "5"))
        loop = asyncio.get_running_loop()
        end = loop.time() + timeout
        try:
            while True:
                left = end - loop.time()
                if left <= 0:
                    break
                try:
                    event = await asyncio.wait_for(q.get(), timeout=left)
                except asyncio.TimeoutError:
                    break
                await resp.write(
                    (json.dumps(event) + "\n").encode())
        except (ConnectionError, ConnectionResetError):
            pass  # client hung up mid-window (normal for watchers)
        finally:
            self.watch_queues.remove(q)
        try:
            await resp.write_eof()
        except (ConnectionError, ConnectionResetError):
            pass
        return resp

    def start(self):
        from aiohttp import web
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self.handle)
        started = threading.Event()
        holder = {}

        def run():
            loop = asyncio.new_event_loop()
            self.loop = loop
            asyncio.set_event_loop(loop)

            async def boot():
                runner = web.AppRunner(app)
                await runner.setup()
                site = web.TCPSite(runner, "127.0.0.1", 0)
                await site.start()
                holder["port"] = site._server.sockets[0].getsockname()[1]
            loop.run_until_complete(boot())
            started.set()
            loop.run_forever()

        threading.Thread(target=run, daemon=True).start()
        started.wait(30)
        self.port = holder["port"]
        return f"http://127.0.0.1:{self.port}"


@pytest.fixture()
def api_server():
    srv = FakeApiServer()
    url = srv.start()
    yield srv, ApiServerKube(base_url=url, token="test-token")
    srv.loop.call_soon_threadsafe(srv.loop.stop)


PIPE = {"apiVersion": "package.tpu-rag.dev/v1alpha1", "kind": "HelmPipeline",
        "metadata": {"name": "p1", "namespace": "default"},
        "spec": {"packages": []}}


def test_apiserver_crud_roundtrip(api_server):
    srv, kube = api_server
    key = ("package.tpu-rag.dev/v1alpha1", "HelmPipeline", "default", "p1")
    assert kube.get(key) is None
    kube.apply(dict(PIPE))
    got = kube.get(key)
    assert got["metadata"]["name"] == "p1"
    assert got["metadata"]["resourceVersion"]
    # upsert adopts the live resourceVersion; spec change lands
    upd = dict(PIPE, spec={"packages": [{"chart": "x"}]})
    kube.apply(upd)
    assert kube.get(key)["spec"]["packages"]
    # stale resourceVersion surfaces as ConflictError
    stale = dict(PIPE)
    stale["metadata"] = dict(PIPE["metadata"], resourceVersion="1")
    with pytest.raises(ConflictError):
        kube.apply(stale)
    assert kube.delete(key)
    assert kube.get(key) is None


def test_apiserver_status_subresource(api_server):
    srv, kube = api_server
    kube.apply(dict(PIPE))
    key = ("package.tpu-rag.dev/v1alpha1", "HelmPipeline", "default", "p1")
    kube.update_status(key, {"phase": "Ready"})
    assert kube.get(key)["status"]["phase"] == "Ready"


def test_apiserver_list_labeled(api_server):
    srv, kube = api_server
    kube.apply({"apiVersion": "v1", "kind": "Service",
                "metadata": {"name": "s1", "namespace": "default",
                             "labels": {"owner": "p1"}}})
    kube.apply({"apiVersion": "apps/v1", "kind": "Deployment",
                "metadata": {"name": "d1", "namespace": "default",
                             "labels": {"owner": "p1"}}})
    kube.apply({"apiVersion": "v1", "kind": "Service",
                "metadata": {"name": "s2", "namespace": "default",
                             "labels": {"owner": "other"}}})
    got = kube.list_labeled("owner", "p1")
    assert {(o["kind"], o["metadata"]["name"]) for o in got} == {
        ("Service", "s1"), ("Deployment", "d1")}


def test_apiserver_watch_streams_events(api_server):
    srv, kube = api_server
    events = []

    def consume():
        for ev in kube.watch("package.tpu-rag.dev/v1alpha1",
                             "HelmPipeline", timeout_seconds=5):
            events.append((ev["type"], ev["object"]["metadata"]["name"]))
            if len(events) >= 3:
                return

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    import time
    time.sleep(0.3)  # let the watch attach
    kube.apply(dict(PIPE))
    kube.apply(dict(PIPE, spec={"packages": [{"chart": "y"}]}))
    kube.delete(("package.tpu-rag.dev/v1alpha1", "HelmPipeline",
                 "default", "p1"))
    t.join(timeout=10)
    assert events == [("ADDED", "p1"), ("MODIFIED", "p1"),
                      ("DELETED", "p1")]


def test_apiserver_leader_election_over_http(api_server):
    """The election protocol runs unchanged over the HTTP client — the
    Lease CRUD + conflict semantics survive the REST round trip."""
    srv, kube = api_server
    clock = Clock()
    a = LeaderElector(kube, "a", lease_seconds=15, clock=clock)
    b = LeaderElector(kube, "b", lease_seconds=15, clock=clock)
    assert a.try_acquire()
    assert not b.try_acquire()
    clock.tick(20)
    assert b.try_acquire()
    lease = kube.get((LEASE_API, "Lease", "kube-system",
                      "tpu-llm-operator"))
    assert lease["spec"]["holderIdentity"] == "b"


def test_resource_path_shapes():
    assert resource_path("v1", "Service", "ns1", "svc") == \
        "/api/v1/namespaces/ns1/services/svc"
    assert resource_path("apps/v1", "Deployment", "ns1") == \
        "/apis/apps/v1/namespaces/ns1/deployments"
    assert resource_path("package.tpu-rag.dev/v1alpha1", "HelmPipeline",
                         "default", "p") == \
        ("/apis/package.tpu-rag.dev/v1alpha1/namespaces/default/"
         "helmpipelines/p")
    assert resource_path("rbac.authorization.k8s.io/v1", "ClusterRole",
                         name="cr") == \
        "/apis/rbac.authorization.k8s.io/v1/clusterroles/cr"


def test_leader_loss_propagates_into_inflight_cycle():
    """ADVICE r5 #2: after a failed renewal, the in-flight while_leading
    cycle used to keep reconciling for a full watch/resync window while
    the new leader reconciled concurrently. run() now hands the cycle a
    ``lost()`` signal flipped by the renewer — a cycle that polls it
    (the operator's one_cycle does) exits within ~a renew interval, so
    the split-brain overlap is bounded well below the cycle length."""
    import time as _time

    kube = InMemoryKube()
    a = LeaderElector(kube, "a", lease_seconds=30)   # real clock
    stop_run = threading.Event()
    cycle_done = []
    in_cycle = threading.Event()

    def cycle(lost):
        in_cycle.set()
        deadline = _time.monotonic() + 20.0   # the "watch window"
        while not lost() and _time.monotonic() < deadline:
            _time.sleep(0.02)
        cycle_done.append(_time.monotonic())
        stop_run.set()

    t = threading.Thread(
        target=lambda: a.run(cycle, renew_seconds=0.05,
                             retry_seconds=0.05, stop=stop_run.is_set),
        daemon=True)
    t.start()
    assert in_cycle.wait(10), "never became leader"
    # Usurp the lease: write holderIdentity over to b with a fresh
    # renewTime, carrying the live resourceVersion — a's next renewal
    # sees an unexpired foreign holder and drops is_leader.
    from generativeaiexamples_tpu.deploy import leader as leader_mod
    cur = kube.get(a.key)
    cur["spec"]["holderIdentity"] = "b"
    cur["spec"]["renewTime"] = leader_mod._fmt(leader_mod._now())
    kube.apply(cur)
    t_usurp = _time.monotonic()
    t.join(timeout=10)
    assert not t.is_alive(), "run() never returned after leadership loss"
    assert cycle_done, "cycle never exited"
    # bounded: the 20 s window was cut short within ~renew interval + poll
    assert cycle_done[0] - t_usurp < 2.0
    assert not a.is_leader


def test_leader_run_zero_arg_callback_still_supported():
    """Legacy zero-argument cycles keep working (cycle-granular loss
    handling): run() inspects the callback's signature rather than
    changing the contract under existing operators."""
    kube = InMemoryKube()
    a = LeaderElector(kube, "a", lease_seconds=15)
    calls = []
    a.run(lambda: calls.append(1), renew_seconds=0.05, retry_seconds=0.05,
          stop=lambda: len(calls) >= 2)
    assert len(calls) >= 2


def test_apiserver_watch_stop_unblocks_quiet_stream(api_server):
    """The leadership-loss signal must tear a QUIET watch stream down:
    with stop flipping shortly after attach, the watch returns in ~a
    poll interval instead of riding out the 30 s server window."""
    import time

    srv, kube = api_server
    t0 = time.monotonic()
    flip_at = t0 + 0.5
    returned = []

    def consume():
        for _ in kube.watch("package.tpu-rag.dev/v1alpha1",
                            "HelmPipeline", timeout_seconds=30,
                            stop=lambda: time.monotonic() >= flip_at):
            pass
        returned.append(time.monotonic())

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive(), "watch did not unblock on stop"
    assert returned and returned[0] - t0 < 5.0  # far below the 30 s window
