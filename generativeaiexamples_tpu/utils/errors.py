"""Typed exception hierarchy for the framework.

Parity with the reference's ``ModelServerException`` hierarchy
(reference: llm-inference-server/model_server/errors.py:20-32), extended to
cover the whole stack. Keeping errors typed lets the serving entrypoint write
k8s termination logs with unwound causes
(reference: model_server/__main__.py:159-193).
"""

from __future__ import annotations


class FrameworkError(Exception):
    """Base class for all first-party errors."""


class ConfigError(FrameworkError):
    """Invalid or missing configuration."""


class ModelLoadError(FrameworkError):
    """A checkpoint could not be found, sniffed, or imported."""


class UnsupportedFormatError(ModelLoadError):
    """Checkpoint format not recognized (reference: model_server/model.py:147-173)."""


class ShardingError(FrameworkError):
    """Invalid mesh/sharding request (e.g. TP*PP != device count;
    reference: model_server/__init__.py:103-110)."""


class EngineError(FrameworkError):
    """Inference-engine runtime failure."""


class SchedulerFullError(EngineError):
    """No free KV slots / queue capacity for a new request."""


class RoleMismatchError(EngineError):
    """A request was submitted to a replica whose disaggregation role
    cannot serve it (e.g. a decode-bound request on a prefill-role
    engine). A routing error, not an engine fault — edges map it to a
    retryable 429, never a breaker trip (docs/disaggregation.md)."""


class RetrievalError(FrameworkError):
    """Vector-store failure. ``reason`` labels which dependency failed
    (``retrieval`` / ``embed``) for degradation metrics."""

    def __init__(self, *args, reason: str = "retrieval"):
        super().__init__(*args)
        self.reason = reason


class BreakerOpenError(FrameworkError):
    """A circuit breaker rejected the call without attempting it
    (utils/resilience.py). Carries the breaker's name and the cooldown
    remaining so edges can emit ``Retry-After`` and degradation paths
    can label their fallback."""

    def __init__(self, *args, breaker: str = "", retry_after_s: float = 0.0):
        super().__init__(*args)
        self.breaker = breaker
        self.retry_after_s = retry_after_s


class ChainError(FrameworkError):
    """Chain-server / example pipeline failure."""


def unwind_causes(exc: BaseException) -> list[str]:
    """Flatten an exception chain into printable lines, innermost last.

    Mirrors the nested-cause unwinding the reference writes to the k8s
    termination log (reference: model_server/__main__.py:168-186).
    """
    lines: list[str] = []
    seen: set[int] = set()
    cur: BaseException | None = exc
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        lines.append(f"{type(cur).__name__}: {cur}")
        cur = cur.__cause__ or (
            None if cur.__suppress_context__ else cur.__context__)
    return lines
