"""Engine tests: continuous batching, streaming, stop conditions, sampling.

Covers what the reference never tested (SURVEY.md §4: no Python tests at
all): greedy determinism vs the pure forward, inflight join/leave, stop
words, queue limits.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from generativeaiexamples_tpu.engine import Engine, EngineConfig, SamplingParams
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import LlamaConfig
from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer
from generativeaiexamples_tpu.ops.sampling import sample
from generativeaiexamples_tpu.utils.errors import EngineError

CFG = LlamaConfig(vocab_size=259 + 5, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  max_position_embeddings=256)

ENGINE_CFG = EngineConfig(max_slots=4, max_input_length=64,
                          max_output_length=32, prefill_buckets=(16, 32, 64),
                          dtype="float32", max_queue=64)


@pytest.fixture(scope="module")
def engine():
    params = llama.init_params(CFG, jax.random.key(7), dtype=jnp.float32)
    eng = Engine(params, CFG, ByteTokenizer(), ENGINE_CFG)
    with eng:
        yield eng


def greedy_reference(params, prompt_ids, n_steps):
    """Pure jnp greedy decode, no engine machinery."""
    ids = list(prompt_ids)
    for _ in range(n_steps):
        tokens = jnp.asarray(np.asarray(ids, np.int32)[None, :])
        pos = jnp.arange(len(ids), dtype=jnp.int32)[None, :]
        logits, _ = llama.apply(params, CFG, tokens, pos)
        ids.append(int(jnp.argmax(logits[0, -1])))
    return ids[len(prompt_ids):]


def test_greedy_matches_pure_forward(engine):
    prompt = engine.tokenizer.encode("hello")
    stream = engine.submit(prompt, SamplingParams(max_tokens=8, top_k=1,
                                                  ignore_eos=True))
    stream.text()
    expected = greedy_reference(engine.params, prompt, 8)
    assert stream.token_ids == expected
    assert stream.finish_reason == "length"


def test_streaming_chunks_concatenate(engine):
    stream = engine.stream_text("abc", SamplingParams(max_tokens=6,
                                                      ignore_eos=True))
    chunks = list(stream)
    assert "".join(chunks) == engine.tokenizer.decode(stream.token_ids)
    assert stream.ttft_ms is not None and stream.ttft_ms > 0


def test_concurrent_requests_join_and_leave(engine):
    """More requests than slots: all must complete (inflight batching)."""
    streams = [engine.submit(engine.tokenizer.encode(f"req {i}"),
                             SamplingParams(max_tokens=4 + i % 3,
                                            ignore_eos=True))
               for i in range(10)]
    for i, s in enumerate(streams):
        s.text()
        assert s.finish_reason == "length"
        assert len(s.token_ids) == 4 + i % 3


def test_determinism_across_batching(engine):
    """A request's greedy output must not depend on its batch-mates."""
    prompt = engine.tokenizer.encode("determinism")
    sp = SamplingParams(max_tokens=6, ignore_eos=True)
    alone = engine.submit(prompt, sp)
    alone.text()
    noise = [engine.submit(engine.tokenizer.encode(f"noise{i}"), sp)
             for i in range(6)]
    again = engine.submit(prompt, sp)
    again.text()
    for s in noise:
        s.text()
    assert alone.token_ids == again.token_ids


def test_stop_words(engine):
    """Stop word cuts the stream (reference: trt_llm.py:211-223)."""
    prompt = engine.tokenizer.encode("stop test")
    free = engine.submit(prompt, SamplingParams(max_tokens=12, ignore_eos=True))
    full_text = free.text()
    if len(full_text) >= 2:
        stop = full_text[1]
        stream = engine.submit(prompt, SamplingParams(
            max_tokens=12, ignore_eos=True, stop_words=[stop]))
        text = stream.text()
        assert stop not in text
        assert stream.finish_reason == "stop"


def test_multi_token_bad_words_banned_mid_stream(engine):
    """A multi-token bad-word sequence never appears in the output: the
    device-side match bans the completing token whenever the generated
    tail equals the sequence prefix (reference: to_word_list_format,
    preprocessing/1/model.py:211)."""
    prompt = engine.tokenizer.encode("sequence ban")
    sp = SamplingParams(max_tokens=24, top_k=1, ignore_eos=True)
    base = engine.submit(prompt, sp)
    base.text()
    toks = base.token_ids
    # Ban the first adjacent pair the unbanned greedy run emits. The pair
    # is injected at the _compile_bad_words seam (byte tokens over 0x7F
    # have no single-character spelling to pass through bad_words=[...];
    # the text->sequence mapping is covered by the over-cap test below
    # and the gRPC single-token test).
    pair = [toks[0], toks[1]]
    orig = engine._compile_bad_words
    engine._compile_bad_words = lambda p: ([], [pair])
    try:
        banned = engine.submit(prompt, sp)
        banned.text()
    finally:
        engine._compile_bad_words = orig
    got = banned.token_ids
    assert pair not in [list(p) for p in zip(got, got[1:])]
    # The ban is on the *sequence*, not its tokens: the first token of
    # the pair stays reachable — greedy decode still opens with it and
    # is only steered away from completing the phrase.
    assert got[0] == pair[0] and got[1] != pair[1]
    assert banned.finish_reason == "length"


def test_bad_words_over_caps_rejected(engine):
    long_word = "x" * (Engine.MAX_BAD_LEN + 1)
    with pytest.raises(EngineError):
        engine.submit(engine.tokenizer.encode("p"), SamplingParams(
            max_tokens=4, bad_words=[long_word]))
    many = [chr(ord("a") + i) + "y" for i in range(Engine.MAX_BAD_SEQS + 1)]
    with pytest.raises(EngineError):
        engine.submit(engine.tokenizer.encode("p"), SamplingParams(
            max_tokens=4, bad_words=many))


def test_bad_words_duplicates_share_table_slots(engine):
    """Duplicate bad_words entries dedupe GLOBALLY before the device
    table cap — N copies of one word must never trip MAX_BAD_SEQS."""
    dupes = ["zy"] * (Engine.MAX_BAD_SEQS + 3)
    _, seqs = engine._compile_bad_words(
        SamplingParams(max_tokens=2, bad_words=dupes))
    assert len(seqs) == 2  # the word's 2 spellings, however many copies
    s = engine.submit(engine.tokenizer.encode("p"), SamplingParams(
        max_tokens=2, top_k=1, ignore_eos=True, bad_words=dupes))
    s.text()
    assert s.finish_reason == "length"


def test_oversized_prompt_rejected(engine):
    with pytest.raises(EngineError):
        engine.submit([5] * 100, SamplingParams())


# ------------------------------------------------------------ int8 KV cache

def test_int8_kv_engine_serves_and_doubles_pages():
    """kv_quant="int8": the engine serves normally over int8 pools, its
    decode path tracks the full-precision engine closely, and the pool
    holds ~2x the pages at the same token budget."""
    params = llama.init_params(CFG, jax.random.key(7), dtype=jnp.float32)
    sp = SamplingParams(max_tokens=10, top_k=1, ignore_eos=True)
    prompt = [(i * 5) % 250 + 3 for i in range(40)]

    def build(kv_quant, tokens=None):
        return Engine(params, CFG, ByteTokenizer(), EngineConfig(
            max_slots=3, max_input_length=64, max_output_length=16,
            prefill_buckets=(16, 64), page_size=16, dtype="float32",
            kv_pool_tokens=tokens, kv_quant=kv_quant))

    ref = build("")
    q8 = build("int8")
    assert set(q8._state["cache"]) == {"k", "v", "ks", "vs"}
    assert q8._state["cache"]["k"].dtype == jnp.int8
    with ref, q8:
        a = ref.submit(prompt, sp)
        b = q8.submit(prompt, sp)
        a.text(), b.text()
    assert b.finish_reason == "length" and len(b.token_ids) == 10
    # greedy decode over the quantized pool stays on the full-precision
    # trajectory for the first steps (error ~0.5%/row; random-init logits
    # are the adversarial case, so only the prefix is pinned)
    assert a.token_ids[:3] == b.token_ids[:3]

    # ~2x pages at a fixed byte budget: same kv_pool_tokens spec resolves
    # to a byte-halved per-token footprint
    assert build("int8")._kv_bytes_per_token() * 2 < \
        build("")._kv_bytes_per_token() * 1.1


def test_int8_kv_deterministic_across_runs():
    params = llama.init_params(CFG, jax.random.key(9), dtype=jnp.float32)
    cfg = EngineConfig(max_slots=2, max_input_length=64,
                       max_output_length=16, prefill_buckets=(32,),
                       page_size=16, dtype="float32", kv_quant="int8")
    outs = []
    for _ in range(2):
        eng = Engine(params, CFG, ByteTokenizer(), cfg)
        with eng:
            s = eng.submit([9] * 20, SamplingParams(max_tokens=8, top_k=1,
                                                    ignore_eos=True))
            s.text()
        outs.append(s.token_ids)
    assert outs[0] == outs[1]


def test_int8_kv_chunked_long_prompt():
    """The chunked paged-prefill admission quantizes chunk KV into the
    pool and later chunks read it back dequantized — long prompts serve
    under kv_quant. NOTE the two engines' pools are NOT bit-identical
    (chunk 2+ attends the dequantized pooled prefix; the one-shot bucket
    attends exact in-register values), so only the leading tokens are
    pinned — the structural contract (chunked admission completes, full
    length generated) is the assertion, not trajectory equality."""
    params = llama.init_params(CFG, jax.random.key(21), dtype=jnp.float32)
    prompt = [(i * 7) % 250 + 3 for i in range(100)]

    def build(cap):
        return Engine(params, CFG, ByteTokenizer(), EngineConfig(
            max_slots=2, max_input_length=128, max_output_length=16,
            prefill_buckets=(32,), page_size=16, dtype="float32",
            kv_pool_tokens=None, steps_per_round=4,
            max_prefill_bucket=cap, kv_quant="int8"))

    chunked = build(32)
    oneshot = build(None)
    sp = SamplingParams(max_tokens=10, top_k=1, ignore_eos=True)
    with chunked, oneshot:
        a = chunked.submit(prompt, sp)
        b = oneshot.submit(prompt, sp)
        a.text(), b.text()
    assert a.finish_reason == b.finish_reason == "length"
    assert len(a.token_ids) == len(b.token_ids) == 10
    assert a.token_ids[:3] == b.token_ids[:3], (a.token_ids, b.token_ids)


def test_empty_prompt_rejected(engine):
    with pytest.raises(EngineError):
        engine.submit([], SamplingParams())


def test_sampling_ops_topk_topp():
    logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0, -1.0]] * 2)
    key = jax.random.key(0)
    # top_k=1 → argmax regardless of temperature
    toks = sample(logits, key, jnp.asarray([5.0, 5.0]),
                  jnp.asarray([1, 1]), jnp.asarray([0.0, 0.0]))
    assert toks.tolist() == [3, 3]
    # top_k=2: only ids {2,3} possible
    many = [sample(logits, jax.random.key(i), jnp.asarray([1.0, 1.0]),
                   jnp.asarray([2, 2]), jnp.asarray([0.0, 0.0])).tolist()
            for i in range(20)]
    seen = {t for pair in many for t in pair}
    assert seen <= {2, 3} and len(seen) == 2
    # top_p tiny → only the argmax survives
    toks = sample(logits, key, jnp.asarray([1.0, 1.0]),
                  jnp.asarray([0, 0]), jnp.asarray([1e-6, 1e-6]))
    assert toks.tolist() == [3, 3]


def test_temperature_zero_is_greedy():
    logits = jnp.asarray([[0.5, 2.5, 1.0]])
    toks = sample(logits, jax.random.key(3), jnp.asarray([0.0]),
                  jnp.asarray([0]), jnp.asarray([0.0]))
    assert toks.tolist() == [1]


def test_repetition_penalty_reduces_repeats(engine):
    prompt = engine.tokenizer.encode("hello")
    plain = engine.submit(prompt, SamplingParams(max_tokens=12, top_k=1,
                                                 ignore_eos=True))
    plain.text()
    pen = engine.submit(prompt, SamplingParams(max_tokens=12, top_k=1,
                                               repetition_penalty=1.8,
                                               ignore_eos=True))
    pen.text()
    # With a random-init model greedy decode degenerates into repeats; the
    # penalty must change the trajectory and strictly reduce repetition.
    def uniq(ids):
        return len(set(ids)) / len(ids)
    assert uniq(pen.token_ids) >= uniq(plain.token_ids)
    if uniq(plain.token_ids) < 1.0:
        assert pen.token_ids != plain.token_ids


def test_paged_pool_backpressure():
    """A KV pool smaller than slots x extent must still serve all requests
    by waiting for pages (the paged-cache capacity-sharing story)."""
    params = llama.init_params(CFG, jax.random.key(7), dtype=jnp.float32)
    cfg = EngineConfig(max_slots=4, max_input_length=64, max_output_length=32,
                       prefill_buckets=(64,), dtype="float32",
                       page_size=32, kv_pool_tokens=96)  # 3 pages + trash
    eng = Engine(params, CFG, ByteTokenizer(), cfg)
    assert eng._n_pages == 4  # 3 usable + trash page 0
    with eng:
        # Each request spans 2 pages (prompt ~10 + 32 out = 42 tokens), so
        # only one fits at a time; all must still complete, in order.
        streams = [eng.submit(eng.tokenizer.encode(f"backpressure {i}"),
                              SamplingParams(max_tokens=32, ignore_eos=True))
                   for i in range(3)]
        for s in streams:
            s.text()
            assert s.finish_reason == "length"
            assert len(s.token_ids) == 32
    assert sorted(eng._free_pages) == [1, 2, 3]  # all pages reclaimed


def test_paged_pool_floors_at_one_full_request():
    """Pool sizing floors at one full-extent request, so admission can never
    deadlock on an accepted request."""
    params = llama.init_params(CFG, jax.random.key(7), dtype=jnp.float32)
    cfg = EngineConfig(max_slots=2, max_input_length=64, max_output_length=32,
                       prefill_buckets=(64,), dtype="float32",
                       page_size=32, kv_pool_tokens=32)  # asks for 1 page
    eng = Engine(params, CFG, ByteTokenizer(), cfg)
    assert eng._n_pages - 1 == eng._pmax  # floored to max_cache_len worth
    with eng:
        s = eng.submit([5] * 60, SamplingParams(max_tokens=32,
                                                ignore_eos=True))
        s.text()
        assert s.finish_reason == "length"


def test_cancel_releases_slot(engine):
    stream = engine.submit(engine.tokenizer.encode("cancel me"),
                           SamplingParams(max_tokens=32, ignore_eos=True))
    stream.cancel()
    for _ in iter(stream):
        pass
    assert stream.finish_reason == "cancelled"
    # The engine must keep serving afterwards.
    ok = engine.submit(engine.tokenizer.encode("after"),
                       SamplingParams(max_tokens=3, ignore_eos=True))
    ok.text()
    assert ok.finish_reason == "length"


def test_greedy_parity_engine_vs_engine_small_rounds(engine):
    """steps_per_round must not affect results: K=1 engine == K=8 engine."""
    params = engine.params
    cfg = EngineConfig(max_slots=2, max_input_length=64, max_output_length=32,
                       prefill_buckets=(16, 32, 64), dtype="float32",
                       steps_per_round=1, dispatch_depth=1)
    eng1 = Engine(params, CFG, ByteTokenizer(), cfg)
    prompt = engine.tokenizer.encode("round parity")
    sp = SamplingParams(max_tokens=10, top_k=1, ignore_eos=True)
    with eng1:
        a = eng1.submit(prompt, sp)
        a.text()
    b = engine.submit(prompt, sp)
    b.text()
    assert a.token_ids == b.token_ids


def test_crash_during_prefill_fails_stream():
    """A device error during admission (compile failure, OOM) must fail the
    request's stream, not leave its consumer blocked forever (regression:
    the request was untracked between queue pop and slot insert)."""
    params = llama.init_params(CFG, jax.random.key(7), dtype=jnp.float32)
    eng = Engine(params, CFG, ByteTokenizer(), ENGINE_CFG)

    def boom(*a, **k):
        raise RuntimeError("synthetic prefill crash")

    eng._prefill_insert = boom
    with eng:
        stream = eng.submit(eng.tokenizer.encode("doomed"),
                            SamplingParams(max_tokens=4))
        with pytest.raises(EngineError):
            stream.text()
    assert stream.finish_reason == "error"


def test_engine_restarts_after_stop():
    params = llama.init_params(CFG, jax.random.key(7), dtype=jnp.float32)
    eng = Engine(params, CFG, ByteTokenizer(), ENGINE_CFG)
    with eng:
        first = eng.generate_text("hi", SamplingParams(max_tokens=3, top_k=1,
                                                       ignore_eos=True))
    # after stop(), a fresh start() must serve again (regression: _stopped
    # was never cleared and restarted engines hung forever)
    with eng:
        second = eng.generate_text("hi", SamplingParams(max_tokens=3, top_k=1,
                                                        ignore_eos=True))
    assert first == second


def test_engine_reset_recovers(tiny_engine_factory=None):
    """reset() abandons the loop, fails live requests, rebuilds device
    state, and serving works again (VERDICT r2 weak #10)."""
    import jax
    import jax.numpy as jnp

    from generativeaiexamples_tpu.engine import (Engine, EngineConfig,
                                                 SamplingParams)
    from generativeaiexamples_tpu.models import llama as _llama
    from generativeaiexamples_tpu.models.configs import LLAMA_TINY
    from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer
    from generativeaiexamples_tpu.utils.errors import EngineError

    params = _llama.init_params(LLAMA_TINY, jax.random.key(0), jnp.float32)
    cfg = EngineConfig(max_slots=2, max_input_length=64,
                       max_output_length=32, prefill_buckets=(32, 64),
                       dtype="float32", page_size=16, kv_pool_tokens=None,
                       steps_per_round=4, dispatch_depth=1)
    eng = Engine(params, LLAMA_TINY, ByteTokenizer(), cfg)
    eng.start()
    assert eng.generate_text("warm", SamplingParams(
        max_tokens=4, top_k=1, ignore_eos=True))

    # a request in flight when reset() lands gets failed, not hung
    stream = eng.submit(eng.tokenizer.encode("pending request"),
                        SamplingParams(max_tokens=8, top_k=1,
                                       ignore_eos=True))
    eng.reset()
    with pytest.raises(EngineError):
        stream.text()

    # the engine is fully serviceable again after reset
    eng.start()
    out = eng.generate_text("after reset", SamplingParams(
        max_tokens=4, top_k=1, ignore_eos=True))
    assert out is not None
    assert eng._fatal is None
    eng.stop()


def test_concurrent_stress_submit_cancel_reset():
    """Race-detection stress (SURVEY §5: the reference ships no -race /
    sanitizer coverage at all): four producer threads hammer
    submit/stream/cancel while the main thread fires reset() twice
    mid-flight. Invariants: no deadlock (bounded wall time), every
    stream reaches a terminal state, and the engine serves correctly
    afterwards — the generation-guard protocol under real contention."""
    import threading
    import time as _time

    params = llama.init_params(CFG, jax.random.key(11), dtype=jnp.float32)
    eng = Engine(params, CFG, ByteTokenizer(), EngineConfig(
        max_slots=4, max_input_length=64, max_output_length=16,
        prefill_buckets=(16, 32), dtype="float32", max_queue=256,
        steps_per_round=4, dispatch_depth=2))
    eng.start()
    eng.generate_text("warm", SamplingParams(max_tokens=2, top_k=1,
                                             ignore_eos=True))
    stop = _time.monotonic() + 8.0
    streams, lock = [], threading.Lock()
    errors = []

    def producer(seed: int):
        i = 0
        while _time.monotonic() < stop:
            i += 1
            try:
                s = eng.submit(eng.tokenizer.encode(f"p{seed}-{i}"),
                               SamplingParams(max_tokens=4 + (i % 5),
                                              top_k=1, ignore_eos=True))
            except Exception as exc:  # noqa: BLE001
                name = type(exc).__name__
                if name not in ("EngineError", "SchedulerFullError"):
                    errors.append(exc)
                continue
            with lock:
                streams.append(s)
            if i % 3 == 0:
                s.cancel()
            elif i % 7 == 0:
                try:
                    s.text()   # block some producers on completion
                except Exception:  # noqa: BLE001 — reset may fail it
                    pass

    threads = [threading.Thread(target=producer, args=(k,), daemon=True)
               for k in range(4)]
    for t in threads:
        t.start()
    _time.sleep(2.0)
    eng.reset()
    eng.start()
    _time.sleep(2.0)
    eng.reset()
    eng.start()
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads), "producer deadlocked"
    assert not errors, errors
    # every stream must reach a terminal state (no orphaned consumers).
    # Poll finish_reason under the deadline BEFORE the blocking read: a
    # truly orphaned stream must fail this assert with a diagnostic, not
    # wedge the test inside text().
    deadline = _time.monotonic() + 60
    for s in streams:
        while s.finish_reason is None and _time.monotonic() < deadline:
            _time.sleep(0.05)
        assert s.finish_reason is not None, "stream never terminated"
        try:
            s.text()
        except Exception:  # noqa: BLE001 — error IS terminal
            pass
    # and the engine still serves correct greedy output
    out = eng.submit(eng.tokenizer.encode("after stress"),
                     SamplingParams(max_tokens=6, top_k=1, ignore_eos=True))
    out.text()
    assert out.token_ids == greedy_reference(
        params, eng.tokenizer.encode("after stress"), 6)
    eng.stop()


def test_stream_text_is_reentrant(engine):
    """Reading a finished stream twice must return the terminal state
    again, not block on the consumed sentinel (regression: the stress
    test's second text() hung forever)."""
    s = engine.submit(engine.tokenizer.encode("twice"),
                      SamplingParams(max_tokens=3, top_k=1, ignore_eos=True))
    first = s.text()
    assert s.text() == ""           # chunks consumed; returns, not hangs
    assert s.finish_reason == "length" and first
    # error terminals are sticky too
    bad = engine.submit(engine.tokenizer.encode("doomed"),
                        SamplingParams(max_tokens=3))
    bad._fail(RuntimeError("synthetic"))
    for _ in range(2):
        with pytest.raises(EngineError):
            bad.text()
    bad.cancel()  # let the loop retire it in the background


def test_long_prompt_chunked_admission_matches_one_shot():
    """Prompts beyond the largest prefill bucket stream through the paged
    pool chunk by chunk (max_prefill_bucket). The chunked admission must
    produce EXACTLY the one-shot engine's output — same greedy tokens,
    same repetition-penalty state accumulated across chunks."""
    params = llama.init_params(CFG, jax.random.key(21), dtype=jnp.float32)
    prompt = [(i * 7) % 250 + 3 for i in range(100)]  # 100 > bucket 32

    def build(cap):
        return Engine(params, CFG, ByteTokenizer(), EngineConfig(
            max_slots=2, max_input_length=128, max_output_length=16,
            prefill_buckets=(32,), page_size=16, dtype="float32",
            kv_pool_tokens=None, steps_per_round=4,
            max_prefill_bucket=cap))

    chunked = build(32)       # buckets capped at 32 -> 4 chunks
    oneshot = build(None)     # auto bucket 128 covers the prompt
    assert chunked._buckets[-1] == 32 and oneshot._buckets[-1] == 128
    for sp in (SamplingParams(max_tokens=10, top_k=1, ignore_eos=True),
               SamplingParams(max_tokens=10, top_k=1, ignore_eos=True,
                              repetition_penalty=1.3)):
        with chunked, oneshot:
            a = chunked.submit(prompt, sp)
            b = oneshot.submit(prompt, sp)
            a.text(), b.text()
        assert a.token_ids == b.token_ids, (a.token_ids, b.token_ids)
        assert a.finish_reason == b.finish_reason == "length"


def test_long_prompt_page_unaligned_and_continuation():
    """Ragged long prompts (not chunk/page multiples) admit correctly and
    decode continues across the chunk boundary; several concurrent long
    and short requests share the pool."""
    params = llama.init_params(CFG, jax.random.key(22), dtype=jnp.float32)
    eng = Engine(params, CFG, ByteTokenizer(), EngineConfig(
        max_slots=3, max_input_length=200, max_output_length=16,
        prefill_buckets=(32,), page_size=16, dtype="float32",
        kv_pool_tokens=None, steps_per_round=4, max_prefill_bucket=32))
    with eng:
        long1 = eng.submit([5] * 77, SamplingParams(max_tokens=6, top_k=1,
                                                    ignore_eos=True))
        short = eng.submit([9] * 10, SamplingParams(max_tokens=6, top_k=1,
                                                    ignore_eos=True))
        long2 = eng.submit([7] * 130, SamplingParams(max_tokens=6, top_k=1,
                                                     ignore_eos=True))
        for s in (long1, short, long2):
            s.text()
            assert s.finish_reason == "length"
            assert len(s.token_ids) == 6
    # parity for one of them against the pure forward
    expected = greedy_reference(params, [5] * 77, 6)
    assert long1.token_ids == expected


def test_long_prompt_padded_span_beyond_window():
    """Regression (review catch): a final chunk whose PADDING runs past
    the extent-derived window used to clamp its scatter start and
    overwrite the prompt's own pages. Geometry chosen so the padded
    chunk span (2 chunks x 64 = 128 tokens) exceeds the extent (77 + 16
    = 93 tokens -> 6 pages + ladder) — output must still equal the
    one-shot engine's."""
    params = llama.init_params(CFG, jax.random.key(23), dtype=jnp.float32)
    prompt = [(i * 11) % 250 + 3 for i in range(77)]   # 77 > C=64

    def build(cap, max_in):
        return Engine(params, CFG, ByteTokenizer(), EngineConfig(
            max_slots=1, max_input_length=max_in, max_output_length=16,
            prefill_buckets=(64,), page_size=16, dtype="float32",
            kv_pool_tokens=None, steps_per_round=4,
            max_prefill_bucket=cap))

    chunked = build(64, 80)   # extent 93 tokens; padded span 128
    oneshot = build(None, 80)
    sp = SamplingParams(max_tokens=10, top_k=1, ignore_eos=True)
    with chunked, oneshot:
        a = chunked.submit(prompt, sp)
        b = oneshot.submit(prompt, sp)
        a.text(), b.text()
    assert a.token_ids == b.token_ids, (a.token_ids, b.token_ids)


def test_stats_expose_pipeline_counters(engine):
    """The overlapped harvest/dispatch pipeline publishes its stage
    counters through engine.stats: cumulative readback-wait time (the
    cost that used to serialize the scheduling loop) and the live
    device-queue depth."""
    import time as _time

    s = engine.submit(engine.tokenizer.encode("counters"),
                      SamplingParams(max_tokens=8, top_k=1,
                                     ignore_eos=True))
    s.text()
    stats = engine.stats
    for key in ("harvest_wait_ms", "harvest_rounds", "first_readback_ms",
                "first_readbacks", "dispatch_queue_depth",
                "dispatch_depth_peak"):
        assert key in stats, f"stats missing pipeline counter {key}"
    assert stats["harvest_rounds"] >= 1
    assert stats["first_readbacks"] >= 1
    assert stats["dispatch_depth_peak"] >= 1
    assert stats["harvest_wait_ms"] >= 0.0
    assert stats["first_readback_ms"] >= 0.0
    # Terminal sentinels are delivered by the harvest worker BEFORE the
    # round's depth decrement, so allow the pipeline a moment to settle;
    # an idle engine must always drain to depth 0.
    deadline = _time.monotonic() + 10
    while engine.stats["dispatch_queue_depth"] and \
            _time.monotonic() < deadline:
        _time.sleep(0.01)
    assert engine.stats["dispatch_queue_depth"] == 0


def test_threaded_harvest_stress_no_orphans():
    """Stress the two-thread pipeline specifically: producers hammer
    submit/cancel (cancel-heavy — host-detected finishes exercise the
    completion queue's release path) while reset() fires mid-flight
    against the harvest worker. Invariants beyond the generic stress
    test: the pipeline itself ends drained (no orphaned in-flight
    entries, depth counter exactly 0), every slot and page is returned,
    and stream terminals stay sticky across a second read."""
    import threading
    import time as _time

    params = llama.init_params(CFG, jax.random.key(29), dtype=jnp.float32)
    eng = Engine(params, CFG, ByteTokenizer(), EngineConfig(
        max_slots=4, max_input_length=64, max_output_length=16,
        prefill_buckets=(16, 32), dtype="float32", max_queue=256,
        steps_per_round=4, dispatch_depth=2))
    eng.start()
    eng.generate_text("warm", SamplingParams(max_tokens=2, top_k=1,
                                             ignore_eos=True))
    stop = _time.monotonic() + 6.0
    streams, lock = [], threading.Lock()
    errors = []

    def producer(seed: int):
        i = 0
        while _time.monotonic() < stop:
            i += 1
            try:
                s = eng.submit(eng.tokenizer.encode(f"h{seed}-{i}"),
                               SamplingParams(max_tokens=6 + (i % 7),
                                              top_k=1, ignore_eos=True))
            except Exception as exc:  # noqa: BLE001
                if type(exc).__name__ not in ("EngineError",
                                              "SchedulerFullError"):
                    errors.append(exc)
                continue
            with lock:
                streams.append(s)
            if i % 2 == 0:   # cancel-heavy: stress the release feedback
                s.cancel()
            elif i % 5 == 0:
                try:
                    s.text()
                except Exception:  # noqa: BLE001 — reset may fail it
                    pass

    threads = [threading.Thread(target=producer, args=(k,), daemon=True)
               for k in range(4)]
    for t in threads:
        t.start()
    _time.sleep(1.5)
    eng.reset()
    eng.start()
    _time.sleep(1.5)
    eng.reset()
    eng.start()
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads), "producer deadlocked"
    assert not errors, errors
    deadline = _time.monotonic() + 60
    for s in streams:
        while s.finish_reason is None and _time.monotonic() < deadline:
            _time.sleep(0.05)
        assert s.finish_reason is not None, "stream never terminated"
        # sticky terminal: a second read returns (or re-raises)
        # immediately instead of blocking on the drained queue
        for _ in range(2):
            try:
                s.text()
            except Exception:  # noqa: BLE001 — error IS terminal
                pass
    # engine still serves correct greedy output after the carnage
    out = eng.submit(eng.tokenizer.encode("after harvest stress"),
                     SamplingParams(max_tokens=6, top_k=1, ignore_eos=True))
    out.text()
    assert out.token_ids == greedy_reference(
        params, eng.tokenizer.encode("after harvest stress"), 6)
    eng.stop()
    # pipeline fully drained: no orphaned in-flight entries, no slot or
    # page leaked, depth counter back to exactly zero
    assert eng._harvest_q.empty()
    assert eng._completed.empty()
    assert eng._inflight_rounds == 0
    assert not eng._slots
    assert sorted(eng._free_slots) == list(range(4))
    cached = (eng._prefix_cache.cached_pages
              if eng._prefix_cache is not None else 0)
    assert len(set(eng._free_pages)) == len(eng._free_pages)
    assert len(eng._free_pages) + cached == eng._n_pages - 1


def test_sampler_occupancy_counters_partial_vs_full(engine):
    """The fused tail's active-slot compaction: a single request on a
    4-slot engine must only pay for ONE sampler row per step (rung 1),
    with the other 3 rows counted as skipped — the proof the
    unembed/sampling tail is sized to occupancy, not max_slots."""
    assert engine._fused_tail
    before = engine.stats
    s = engine.submit(engine.tokenizer.encode("occupancy"),
                      SamplingParams(max_tokens=10, top_k=1,
                                     ignore_eos=True))
    s.text()
    after = engine.stats
    sampled = after["sampler_rows_sampled"] - before["sampler_rows_sampled"]
    skipped = after["sampler_rows_skipped"] - before["sampler_rows_skipped"]
    assert sampled > 0
    # one active slot on a 4-slot engine: every decode step samples 1
    # row and skips exactly max_slots - 1 = 3
    assert skipped == 3 * sampled


def test_greedy_parity_fused_vs_materialized_tail(engine, monkeypatch):
    """ENGINE_FUSED_SAMPLER=0 keeps the classic materialized
    unembed+penalize+argmax tail (the mesh-serving/oracle path); greedy
    tokens must be identical either way — the fused tile stream computes
    the same logits, just never as one (B, V) buffer."""
    prompt = engine.tokenizer.encode("fused parity probe")
    sp = SamplingParams(max_tokens=12, top_k=1, ignore_eos=True)
    want = engine.submit(prompt, sp)
    want.text()

    monkeypatch.setenv("ENGINE_FUSED_SAMPLER", "0")
    oracle = Engine(engine.params, CFG, ByteTokenizer(), ENGINE_CFG)
    with oracle:
        assert not oracle._fused_tail
        got = oracle.submit(prompt, sp)
        got.text()
    assert got.token_ids == want.token_ids
