"""gRPC LLMService tests (reference: GrpcTritonClient semantics,
model_server_client/trt_llm.py:370-499 — streaming deltas, final-response
flag, readiness polling, invalid-argument surfacing)."""

import grpc
import jax
import jax.numpy as jnp
import pytest

from generativeaiexamples_tpu.engine import Engine, EngineConfig
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import LLAMA_TINY
from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer
from generativeaiexamples_tpu.serving.grpc_server import (GrpcLLMClient,
                                                          serve_grpc)


@pytest.fixture(scope="module")
def served():
    params = llama.init_params(LLAMA_TINY, jax.random.key(0),
                               dtype=jnp.float32)
    cfg = EngineConfig(max_slots=2, max_input_length=256,
                       max_output_length=64, prefill_buckets=(32, 64, 256),
                       dtype="float32", page_size=16, kv_pool_tokens=None,
                       steps_per_round=4, dispatch_depth=1)
    engine = Engine(params, LLAMA_TINY, ByteTokenizer(), cfg)
    from generativeaiexamples_tpu.embed.encoder import get_embedder
    embedder = get_embedder("hash", "hash", dim=32)
    server = serve_grpc(engine, "llama-tiny", embedder, max_output=64,
                        host="127.0.0.1", port=0)
    client = GrpcLLMClient(f"127.0.0.1:{server._bound_port}")
    client.wait_ready()
    yield client
    client.close()
    server.stop(grace=None)
    engine.stop()


def test_grpc_health(served):
    resp = served.wait_ready()
    assert resp.ready and resp.model_name == "llama-tiny"


def test_grpc_generate_unary(served):
    out = served.generate("hello tpu", max_tokens=8, top_k=1,
                          ignore_eos=True)
    assert isinstance(out, str) and len(out) > 0


def test_grpc_generate_stream_matches_unary(served):
    kw = dict(max_tokens=8, top_k=1, ignore_eos=True)
    unary = served.generate("stream me", **kw)
    chunks = list(served.generate_stream("stream me", **kw))
    assert "".join(chunks) == unary


def test_grpc_invalid_argument(served):
    with pytest.raises(grpc.RpcError) as err:
        served.generate("x" * 500, max_tokens=4)   # over max_input_length
    assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    with pytest.raises(grpc.RpcError) as err:
        served.generate("ok", length_penalty=2.0)
    assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_grpc_embed(served):
    emb = served.embed(["alpha", "beta"], input_type="passage")
    assert emb.shape == (2, 32)
    q = served.embed(["alpha"], input_type="query")
    assert q.shape == (1, 32)


def test_grpc_bad_words_single_token(served):
    """A banned single-token word never appears; greedy decode picks the
    next-best token instead."""
    base = served.generate("ban test", max_tokens=12, top_k=1,
                           ignore_eos=True)
    assert base
    banned_char = base[0]
    out = served.generate("ban test", max_tokens=12, top_k=1,
                          ignore_eos=True, bad_words=[banned_char])
    assert banned_char not in out


def test_grpc_bad_words_over_cap_rejected(served):
    """Multi-token bans are served device-side, but a sequence longer than
    the engine's table (MAX_BAD_LEN) is rejected loudly, not truncated."""
    with pytest.raises(grpc.RpcError) as err:
        served.generate("x", max_tokens=4,
                        bad_words=["far too long a phrase to fit the table"])
    assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
