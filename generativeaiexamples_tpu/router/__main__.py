"""CLI: serve the fleet router, or drain a replica for a rollout.

Serve::

    python -m generativeaiexamples_tpu.router serve \\
        --replicas r0=http://chain-0:8081,r1=http://chain-1:8081 \\
        --port 8080 [--policy affinity]

Drain (what the k8s preStop hook runs — POST ``/control/drain`` on the
replica, then poll its ``/health`` until the in-flight stream count
reaches 0 or the wait budget expires)::

    python -m generativeaiexamples_tpu.router drain \\
        --url http://127.0.0.1:8081 --wait 120

Undrain (rollback — re-open admission on a drained replica)::

    python -m generativeaiexamples_tpu.router undrain \\
        --url http://127.0.0.1:8081
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Optional

from ..utils.logging import get_logger

logger = get_logger(__name__)


def parse_replicas(spec: str) -> list[tuple[str, str]]:
    """``name=url,name=url`` (or bare ``url,url`` → auto-named r0, r1…).

    Duplicate names are a hard error: ``ReplicaTable.add`` is
    last-writer-wins (the re-add/rollout story), so a collision —
    including a bare URL auto-named into an explicit name — would
    silently drop a replica from the fleet."""
    out: list[tuple[str, str]] = []
    seen: set[str] = set()
    for i, entry in enumerate(e.strip() for e in spec.split(",")):
        if not entry:
            continue
        if "=" in entry and not entry.startswith(("http://", "https://")):
            name, _, url = entry.partition("=")
            name, url = name.strip(), url.strip()
        else:
            name, url = f"r{i}", entry
        if name in seen:
            raise ValueError(f"duplicate replica name {name!r} in "
                             f"--replicas (auto-named bare URLs use "
                             f"their position: r0, r1, ...)")
        seen.add(name)
        out.append((name, url))
    return out


def drain(url: str, wait_s: float, poll_s: float = 1.0) -> int:
    """Flip the replica to draining (``serving.client.drain_replica`` —
    one implementation of the protocol), then wait for in-flight 0 by
    polling ``/health`` (a drained replica answers 503, and that body
    IS the signal the poll reads)."""
    import requests

    from ..serving.client import drain_replica

    url = url.rstrip("/")
    try:
        body = drain_replica(url)
    except requests.RequestException as exc:
        print(f"drain: POST /control/drain failed: {exc}",
              file=sys.stderr)
        return 1
    in_flight = int(body.get("in_flight", 0))
    print(f"drain: admission closed, {in_flight} stream(s) in flight")
    deadline = time.monotonic() + wait_s
    while in_flight > 0 and time.monotonic() < deadline:
        time.sleep(poll_s)
        try:
            health = requests.get(f"{url}/health", timeout=10.0).json()
            in_flight = int((health.get("load") or {}).get(
                "in_flight", in_flight))
        except requests.RequestException as exc:
            print(f"drain: health poll failed ({exc}); assuming drained")
            return 0
        except ValueError:
            pass  # non-JSON health answer; keep the last known count
    if in_flight > 0:
        print(f"drain: {in_flight} stream(s) still in flight after "
              f"{wait_s}s wait budget", file=sys.stderr)
        return 2
    print("drain: all in-flight streams finished")
    return 0


def undrain(url: str) -> int:
    """Re-open admission on a drained replica (rollback)."""
    import requests

    from ..serving.client import undrain_replica

    try:
        body = undrain_replica(url.rstrip("/"))
    except requests.RequestException as exc:
        print(f"undrain: POST /control/undrain failed: {exc}",
              file=sys.stderr)
        return 1
    print(f"undrain: admission reopened "
          f"({body.get('in_flight', 0)} stream(s) in flight)")
    return 0


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(description="TPU RAG fleet router")
    sub = parser.add_subparsers(dest="cmd")

    serve_p = sub.add_parser("serve", help="run the router")
    serve_p.add_argument("--replicas", default=os.environ.get(
        "ROUTER_REPLICAS", ""), help="name=url,... or url,...")
    serve_p.add_argument("--host", default="0.0.0.0")
    serve_p.add_argument("--port", type=int, default=8080)
    serve_p.add_argument("--policy", default=None,
                         choices=("affinity", "round_robin"))
    serve_p.add_argument("--autoscale", action="store_true",
                         help="attach the SLO-driven autoscale "
                              "controller (docs/autoscaling.md; knobs "
                              "via ROUTER_AUTOSCALE_* env) — same as "
                              "ROUTER_AUTOSCALE=1")
    serve_p.add_argument("--min-replicas", type=int, default=None,
                         help="autoscale floor (ROUTER_AUTOSCALE_MIN)")
    serve_p.add_argument("--max-replicas", type=int, default=None,
                         help="autoscale ceiling (ROUTER_AUTOSCALE_MAX; "
                              "default: the --replicas count)")

    drain_p = sub.add_parser("drain", help="drain one replica (preStop)")
    drain_p.add_argument("--url", required=True)
    drain_p.add_argument("--wait", type=float, default=120.0,
                         help="seconds to wait for in-flight streams")
    drain_p.add_argument("--poll", type=float, default=1.0)

    undrain_p = sub.add_parser(
        "undrain", help="re-open admission on a drained replica")
    undrain_p.add_argument("--url", required=True)

    args = parser.parse_args(argv)
    if args.cmd == "drain":
        return drain(args.url, args.wait, args.poll)
    if args.cmd == "undrain":
        return undrain(args.url)
    if args.cmd != "serve":
        parser.print_help()
        return 2

    from aiohttp import web

    from ..utils.logging import write_pid_file
    from .server import create_router_app

    try:
        replicas = parse_replicas(args.replicas)
    except ValueError as exc:
        print(f"serve: {exc}", file=sys.stderr)
        return 2
    # Pid file under the run dir ($GAIE_RUN_DIR) like both servers —
    # launcher lines used to `echo $! > router.pid` at the repo root
    # (PR-10 rehomed the servers' pids; the router missed). Logs go to
    # stderr; redirect them under $GAIE_RUN_DIR too, never the repo.
    write_pid_file(f"router-{args.port}")
    if not replicas:
        print("serve: --replicas (or ROUTER_REPLICAS) is required",
              file=sys.stderr)
        return 2
    autoscale = None
    if args.autoscale or os.environ.get("ROUTER_AUTOSCALE", "") \
            not in ("", "0", "false", "off"):
        from .autoscale import AutoscaleController, AutoscalePolicy

        def autoscale_factory(router):
            policy = AutoscalePolicy.from_env(
                min_replicas=args.min_replicas,
                max_replicas=(args.max_replicas
                              if args.max_replicas is not None
                              else (None if os.environ.get(
                                  "ROUTER_AUTOSCALE_MAX")
                                  else len(replicas))))
            return AutoscaleController(router, policy=policy,
                                       surge=router.surge)
        autoscale = autoscale_factory
    app = create_router_app(replicas, policy=args.policy,
                            autoscale_factory=autoscale)
    web.run_app(app, host=args.host, port=args.port)
    return 0


if __name__ == "__main__":
    sys.exit(main())
